"""Minimal OpenTelemetry-style tracing with OTLP/HTTP export.

The reference traces its mutating webhook with OTel — a lazily-created tracer
(sync.OnceValue, notebook_mutating_webhook.go:74-76), a root span per
admission with notebook attributes (:366-373), child spans, and span events
that the test suite asserts on via an in-memory exporter
(opentelemetry_test.go:26-78).  We keep the same shape: a process-global
provider that defaults to noop, swappable for an InMemorySpanExporter in
tests — tracing as a test observability channel — plus an OtlpHttpExporter
(the OTLP/HTTP JSON protocol, POST {endpoint}/v1/traces) so spans leave the
process in production: set OTEL_EXPORTER_OTLP_ENDPOINT and the manager
wires it at startup (setup_exporter_from_env).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Iterator, Optional

logger = logging.getLogger("kubeflow_tpu.tracing")

# injectable time source so span timelines are deterministic under a
# FakeClock (set_clock); None falls back to the wall clock
_clock = None


def set_clock(clock) -> None:
    """Route span/event timestamps through `clock.now()` (a FakeClock in
    tests makes trace timelines deterministic); None restores time.time."""
    global _clock
    _clock = clock


def _now() -> float:
    c = _clock
    return c.now() if c is not None else time.time()


@dataclass
class SpanEvent:
    name: str
    attributes: dict = field(default_factory=dict)
    timestamp: float = 0.0


@dataclass
class Span:
    name: str
    attributes: dict = field(default_factory=dict)
    events: list[SpanEvent] = field(default_factory=list)
    parent: Optional["Span"] = None
    start_time: float = 0.0
    end_time: float = 0.0
    recording: bool = True
    # W3C-style ids (hex): all spans of one trace share trace_id
    trace_id: str = ""
    span_id: str = ""

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        if self.recording:
            self.events.append(SpanEvent(name, dict(attributes or {}), _now()))

    def set_attribute(self, key: str, value) -> None:
        if self.recording:
            self.attributes[key] = value


_NOOP_SPAN = Span(name="", recording=False)

# The active-span stack, shared by every Tracer in the process (OTel's
# context propagation): a child span started anywhere inside a reconcile —
# a controller phase, the admission webhook re-entered through an ApiServer
# write, a fault injection — parents onto the live reconcile span.  A
# contextvar is per-thread (and per-async-task), so threaded managers and
# webhook callouts cannot cross-contaminate each other's stacks.
_SPAN_STACK: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "kubeflow_tpu_span_stack", default=())


def current_span() -> Span:
    """The innermost live span on this thread/context (noop when none) —
    the hook kube.faults uses to stamp injected faults onto whichever
    reconcile attempt the fault actually hit."""
    stack = _SPAN_STACK.get()
    return stack[-1] if stack else _NOOP_SPAN


class InMemorySpanExporter:
    """Collects finished spans for test assertions
    (opentelemetry_test.go InMemoryExporter analog)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: list[Span] = []

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def events(self) -> list[str]:
        return [e.name for s in self.spans for e in s.events]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class Tracer:
    def __init__(self, name: str) -> None:
        self.name = name

    def current_span(self) -> Span:
        return current_span()

    @contextlib.contextmanager
    def start_span(
        self, name: str, attributes: Optional[dict] = None,
        trace_id: str = "",
    ) -> Iterator[Span]:
        """Open a span as a child of the context's current span.  For a ROOT
        span (no parent on the stack) `trace_id` pins the trace identity —
        the manager passes the same id for every retry of one reconcile
        request so its attempts line up on one trace timeline."""
        # the exporter is resolved per-span, matching the reference's lazily
        # created tracer whose provider is swapped in by tests
        exporter = _exporter
        if exporter is None:
            yield _NOOP_SPAN
            return
        stack = _SPAN_STACK.get()
        parent = stack[-1] if stack else None
        span = Span(
            name=name,
            attributes=dict(attributes or {}),
            parent=parent,
            start_time=_now(),
            trace_id=parent.trace_id if parent
            else (trace_id or os.urandom(16).hex()),
            span_id=os.urandom(8).hex(),
        )
        token = _SPAN_STACK.set(stack + (span,))
        try:
            yield span
        finally:
            _SPAN_STACK.reset(token)
            span.end_time = _now()
            exporter.export(span)


def _otlp_value(v) -> dict:
    """Encode one attribute value as an OTLP AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: dict) -> list[dict]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


def _nanos(t: float) -> str:
    return str(int(t * 1e9))


class OtlpHttpExporter:
    """OTLP/HTTP JSON span exporter: POST {endpoint}/v1/traces.

    The production counterpart of the test InMemorySpanExporter — the
    reference's webhook tracing is real OpenTelemetry with a pluggable
    provider (notebook_mutating_webhook.go:74-76); this speaks the OTLP
    wire format any collector accepts.  Spans are buffered and flushed by a
    background thread (batch span processor shape); export failures are
    logged and dropped — tracing must never take down the control plane."""

    def __init__(self, endpoint: str, service_name: str = "kubeflow-tpu",
                 headers: Optional[dict] = None,
                 flush_interval_s: float = 5.0, max_batch: int = 512,
                 timeout_s: float = 10.0) -> None:
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.headers = dict(headers or {})
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._buffer: list[Span] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    def export(self, span: Span) -> None:
        with self._lock:
            self._buffer.append(span)
            full = len(self._buffer) >= self.max_batch
        if full:
            self.flush()

    def encode(self, spans: list[Span]) -> dict:
        """ExportTraceServiceRequest JSON for a batch of finished spans."""
        return {"resourceSpans": [{
            "resource": {"attributes": _otlp_attrs(
                {"service.name": self.service_name})},
            "scopeSpans": [{
                "scope": {"name": "kubeflow_tpu.utils.tracing"},
                "spans": [{
                    "traceId": s.trace_id,
                    "spanId": s.span_id,
                    **({"parentSpanId": s.parent.span_id}
                       if s.parent is not None else {}),
                    "name": s.name,
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": _nanos(s.start_time),
                    "endTimeUnixNano": _nanos(s.end_time),
                    "attributes": _otlp_attrs(s.attributes),
                    "events": [{
                        "timeUnixNano": _nanos(e.timestamp),
                        "name": e.name,
                        "attributes": _otlp_attrs(e.attributes),
                    } for e in s.events],
                } for s in spans],
            }],
        }]}

    def flush(self) -> None:
        with self._lock:
            batch, self._buffer = self._buffer, []
        if not batch:
            return
        body = json.dumps(self.encode(batch)).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json", **self.headers})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception as err:  # noqa: BLE001 — drop, never crash
            logger.warning("OTLP export of %d spans failed: %s",
                           len(batch), err)

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            self.flush()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.timeout_s)
        self.flush()


_provider_lock = threading.Lock()
_exporter = None  # anything with .export(Span)


def set_exporter(exporter) -> None:
    """Install the process-wide exporter (InMemorySpanExporter in tests,
    OtlpHttpExporter in production); None restores noop."""
    global _exporter
    with _provider_lock:
        _exporter = exporter


def setup_exporter_from_env(env=None):
    """Install an OtlpHttpExporter when OTEL_EXPORTER_OTLP_ENDPOINT is set
    (the standard OTel env contract; OTEL_SERVICE_NAME optional).  Returns
    the exporter (caller owns shutdown()) or None."""
    env = env if env is not None else os.environ
    endpoint = env.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
    if not endpoint:
        return None
    exporter = OtlpHttpExporter(
        endpoint, service_name=env.get("OTEL_SERVICE_NAME", "kubeflow-tpu"))
    set_exporter(exporter)
    logger.info("OTLP trace export -> %s", exporter.url)
    return exporter


def get_tracer(name: str) -> Tracer:
    """Tracer whose exporter is resolved at each span start, matching the
    reference's OnceValue'd tracer that resolves the provider lazily."""
    return Tracer(name)
