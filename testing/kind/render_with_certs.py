"""Render the webhook-enabled profile for a real cluster with minted certs.

The reference's integration lane generates a self-signed CA, patches its
caBundle into the webhook configurations, and hands the serving pair to
the controller via a Secret
(/root/reference/.github/workflows/odh_notebook_controller_integration_test.yaml:196-218,
components/testing/gh-actions/install_cert_manager.sh role).  This script
is that step without cert-manager: mint a CA + serving cert for the
webhook Service DNS names (kube/certs.py), emit (a) the full profile with
caBundle patched into the Mutating/Validating webhook configs AND the CRD
conversion clause, and (b) the tls Secret the manager Deployment mounts.

Usage: python testing/kind/render_with_certs.py --namespace NS --image IMG \
         > /tmp/manifests.yaml
"""

from __future__ import annotations

import argparse
import base64
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import yaml  # noqa: E402

from kubeflow_tpu.deploy.manifests import render_profile  # noqa: E402
from kubeflow_tpu.kube.certs import mint_serving_cert  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--namespace", default="kubeflow-tpu-system")
    parser.add_argument("--image", default="kubeflow-tpu-controller:kind")
    parser.add_argument("--profile", default="kubeflow")
    args = parser.parse_args()

    svc = "notebook-controller-webhook"
    bundle = mint_serving_cert(
        common_name=svc,
        dns_names=(svc, f"{svc}.{args.namespace}",
                   f"{svc}.{args.namespace}.svc",
                   f"{svc}.{args.namespace}.svc.cluster.local"),
    )
    ca_b64 = base64.b64encode(bundle.ca_cert_pem).decode()

    docs = render_profile(args.profile, image=args.image)
    for doc in docs:
        kind = doc.get("kind", "")
        if kind in ("MutatingWebhookConfiguration",
                    "ValidatingWebhookConfiguration"):
            for wh in doc.get("webhooks", []):
                wh["clientConfig"]["caBundle"] = ca_b64
        elif kind == "CustomResourceDefinition":
            conv = doc["spec"].get("conversion", {})
            if conv.get("strategy") == "Webhook":
                conv["webhook"]["clientConfig"]["caBundle"] = ca_b64

    docs.append({
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {"name": f"{svc}-certs"},
        "type": "kubernetes.io/tls",
        "data": {
            "tls.crt": base64.b64encode(bundle.cert_pem).decode(),
            "tls.key": base64.b64encode(bundle.key_pem).decode(),
        },
    })
    print(yaml.safe_dump_all(docs, sort_keys=False))


if __name__ == "__main__":
    main()
