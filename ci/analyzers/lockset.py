"""Static race detector: lock-inconsistent field access (lockset).

In the concurrency core (the same modules lock_order.py graphs, plus the
slice scheduler), an instance field that SOME method protects with a
lock is a shared mutable — every other access must hold a lock too.  A
field with both guarded and unguarded accesses outside ``__init__`` is
flagged once, per field: either the unguarded site is a real race (the
PR 9 class of bug the interleave explorer hunts dynamically) or it is a
reasoned exception (GIL-atomic counters, single-writer telemetry) that
belongs in allowlist.py with its reason written down.

Mechanics (stdlib ``ast``, one pass per module):

  - a *lock* is a ``self.<attr>`` whose name contains lock/mutex and
    that the class acquires via ``with self.<attr>:`` (or holds through
    ``ExitStack.enter_context``);
  - every other ``self.<attr>`` load/store in a method body is a field
    access, labelled with the set of locks held at that point;
  - a private method called only while a lock is held INHERITS it: its
    entry lockset is the intersection of the locksets at its intra-class
    call sites (fixpoint) — the ``_register_entry``-style "caller holds
    the lock" idiom needs no annotation;
  - nested functions (retry closures) are scanned with the lockset at
    their definition point — they run inline in these modules.

Constructor writes are exempt (no concurrent readers exist before
``__init__`` returns), as are fields only ever read after construction —
a *write* being an attribute rebind, a subscript store/delete, or a
mutating container method (append/add/pop/...).  Call sites inside
``__init__`` likewise don't count against lock inheritance.
"""

from __future__ import annotations

import ast
from collections import defaultdict

from . import Module, Violation

CHECK = "lockset"

#: concurrency core: every class here is touched from watch/worker
#: threads and the manager loop at once
LOCK_MODULES = (
    "kubeflow_tpu/kube/store.py",
    "kubeflow_tpu/kube/cache.py",
    "kubeflow_tpu/kube/cluster.py",
    "kubeflow_tpu/kube/controller.py",
    "kubeflow_tpu/core/scheduler.py",
)

_LOCKISH = ("lock", "mutex")

#: container methods that mutate their receiver — `self.x.append(...)`
#: is a write to the shared structure behind `self.x`
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "__setitem__", "__delitem__",
})


def _is_self_attr(node) -> str:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _lockish(attr: str) -> bool:
    low = attr.lower()
    return any(p in low for p in _LOCKISH)


class _Access:
    __slots__ = ("method", "held", "line", "write")

    def __init__(self, method, held, line, write):
        self.method = method
        self.held = held       # frozenset of lock attr names (with-held)
        self.line = line
        self.write = write


class _ClassScan:
    def __init__(self, cls: ast.ClassDef) -> None:
        self.cls = cls
        self.methods: dict[str, ast.FunctionDef] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.locks: set[str] = set()
        self.accesses: dict[str, list[_Access]] = defaultdict(list)
        # callee -> [(caller method, with-held locks at the call site)]
        self.callsites: dict[str, list[tuple[str, frozenset]]] = \
            defaultdict(list)
        for name, fn in self.methods.items():
            self._scan(name, fn.body, frozenset())

    # -- per-method walk ------------------------------------------------------
    def _scan(self, method: str, stmts, held) -> None:
        for stmt in stmts:
            self._scan_stmt(method, stmt, held)

    def _scan_stmt(self, method: str, stmt, held) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                attr = _is_self_attr(item.context_expr)
                if attr and _lockish(attr):
                    self.locks.add(attr)
                    inner = inner | {attr}
                else:
                    self._scan_expr(method, item.context_expr, held)
            self._scan(method, stmt.body, inner)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # retry closures and watch callbacks: scanned with the
            # lockset at their definition point
            self._scan(method, stmt.body, held)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub:
                self._scan(method, sub, held)
        for h in getattr(stmt, "handlers", ()) or ():
            self._scan(method, h.body, held)
        for name in stmt._fields:
            sub = getattr(stmt, name, None)
            if name in ("body", "orelse", "finalbody", "handlers"):
                continue
            for node in sub if isinstance(sub, list) else [sub]:
                if isinstance(node, ast.AST):
                    self._scan_expr(method, node, held)

    def _scan_expr(self, method: str, expr, held) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                func = node.func
                callee = _is_self_attr(func)
                if callee and callee in self.methods:
                    self.callsites[callee].append((method, held))
                elif isinstance(func, ast.Attribute) and \
                        func.attr in _MUTATORS:
                    self._record(method, _is_self_attr(func.value),
                                 held, node.lineno, write=True)
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                self._record(method, _is_self_attr(node.value),
                             held, node.lineno, write=True)
            attr = _is_self_attr(node)
            if attr:
                self._record(method, attr, held, node.lineno,
                             write=isinstance(node.ctx,
                                              (ast.Store, ast.Del)))

    def _record(self, method, attr, held, line, write) -> None:
        if attr and not _lockish(attr) and attr not in self.methods:
            self.accesses[attr].append(_Access(method, held, line, write))

    # -- inherited locksets (fixpoint) ----------------------------------------
    def entry_locksets(self) -> dict[str, frozenset]:
        """Entry lockset per method: the intersection over every
        intra-class call site of (locks held at the site ∪ the caller's
        own entry lockset).  Only private helpers inherit — a public
        method is callable from outside the class with nothing held.
        Seeded full and refined down, so call cycles converge."""

        def inherits(name: str) -> bool:
            return name.startswith("_") and not name.startswith("__")

        # construction-time call sites can't race — they don't dilute
        # the intersection
        callsites = {
            name: [(c, h) for c, h in sites if c != "__init__"]
            for name, sites in self.callsites.items()}
        entry = {name: (frozenset(self.locks)
                        if inherits(name) and callsites.get(name)
                        else frozenset())
                 for name in self.methods}
        changed = True
        while changed:
            changed = False
            for name, sites in callsites.items():
                if not inherits(name):
                    continue
                got = None
                for caller, held in sites:
                    site = held | entry.get(caller, frozenset())
                    got = site if got is None else (got & site)
                got = got if got is not None else frozenset()
                if got != entry[name]:
                    entry[name] = got
                    changed = True
        return entry


def analyze(mod: Module) -> list[Violation]:
    if mod.rel not in LOCK_MODULES:
        return []
    out: list[Violation] = []
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        scan = _ClassScan(node)
        if not scan.locks:
            continue
        entry = scan.entry_locksets()
        for field in sorted(scan.accesses):
            accs = [a for a in scan.accesses[field]
                    if a.method != "__init__"]
            if not accs or not any(a.write for a in accs):
                continue   # read-only after construction

            def lockset(a: _Access) -> frozenset:
                return a.held | entry.get(a.method, frozenset())

            guarded = [a for a in accs if lockset(a) & scan.locks]
            naked = [a for a in accs if not (lockset(a) & scan.locks)]
            if not guarded or not naked:
                continue
            locks = sorted(set().union(
                *(lockset(a) & scan.locks for a in guarded)))
            first = min(naked, key=lambda a: a.line)
            where = sorted({f"{a.method}:{a.line}" for a in naked})
            out.append(Violation(
                CHECK, mod.rel, first.line, f"{node.name}.{field}",
                "field is guarded by %s in %d place(s) but accessed "
                "without any lock at %s — either a data race or an "
                "allowlist.py entry with its reason" % (
                    "/".join(locks), len(guarded), ", ".join(where))))
    return out
