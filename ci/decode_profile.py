"""Decode bottleneck profiler (run on the TPU chip).

Times KV-cache decode variants against the honest HBM traffic model
(weights + full-cache reads per step) and measures achievable HBM read
bandwidth directly, so the roofline is grounded in what this chip+relay
actually delivers rather than the spec sheet.

Usage: python ci/decode_profile.py [--quick]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.configs import BENCH_CHIP
from kubeflow_tpu.models.generate import decode_config, generate
from kubeflow_tpu.models.transformer import Transformer


def measure_hbm_read_gbps() -> float:
    """Achievable HBM read bandwidth: sum-reduce a 4 GiB bf16 array.

    The reduce reads every byte once and writes almost nothing; best of
    several windows rejects the relay's half-speed interference.
    """
    n = 2 * 1024**3  # 2Gi elements * 2B = 4 GiB
    x = jnp.ones((n,), jnp.bfloat16)
    f = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
    np.asarray(f(x))  # compile + warmup
    best = 0.0
    for _ in range(4):
        t0 = time.perf_counter()
        np.asarray(f(x))
        dt = time.perf_counter() - t0
        best = max(best, 2.0 * n / dt / 1e9)
    return best


def decode_traffic_bytes(cfg, batch: int) -> dict:
    """Per-step HBM traffic of one decode step: every bf16 weight streamed
    once + the full KV cache read once (the static-shape cache reads
    max_seq_len regardless of fill)."""
    w = cfg.num_params * 2
    kv = (2 * batch * cfg.max_seq_len * cfg.num_kv_heads * cfg.head_dim
          * 2 * cfg.num_layers)
    return {"weight_bytes": w, "kv_bytes": kv, "total": w + kv}


def time_variant(name: str, cfg, batch: int, prompt_len: int,
                 new_tokens: int, windows: int = 3,
                 unroll_layers: bool = True) -> float:
    model = Transformer(cfg)
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(rng, (batch, prompt_len), 0, cfg.vocab_size)
    params = jax.jit(model.init)(rng, prompt)["params"]
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    run = jax.jit(lambda p, t: generate(cfg, p, t, new_tokens,
                                        unroll_layers=unroll_layers))
    np.asarray(run(params, prompt))
    best = 0.0
    for i in range(windows):
        p = jax.random.randint(jax.random.PRNGKey(1000 + i),
                               (batch, prompt_len), 0, cfg.vocab_size)
        np.asarray(p)
        t0 = time.perf_counter()
        np.asarray(run(params, p))
        dt = time.perf_counter() - t0
        best = max(best, batch * new_tokens / dt)
    traffic = decode_traffic_bytes(cfg, batch)
    step_s = batch / best
    eff_gbps = traffic["total"] / step_s / 1e9
    print(f"{name}: {best:,.0f} tok/s  step={step_s*1e3:.2f}ms  "
          f"traffic={traffic['total']/1e6:.0f}MB/step  "
          f"effective={eff_gbps:.0f} GB/s")
    return best


def main() -> None:
    quick = "--quick" in sys.argv
    if "--probe-bw" in sys.argv:
        # NOTE: this probe reads ~55 GB/s — useless through the relay
        # (~120ms fixed round-trip swamps sub-second measurements; the
        # decode loop itself demonstrates 540+ GB/s effective).  Kept
        # behind a flag for when the code runs without the relay.
        gbps = measure_hbm_read_gbps()
        print(f"hbm read probe: {gbps:.0f} GB/s (spec 819; see note)")

    batch, prompt_len, new_tokens = 16, 128, 256
    base = BENCH_CHIP.with_(max_seq_len=prompt_len + new_tokens)

    # variant A keeps nn.scan over layers (the round-3 shipped program:
    # the KV cache re-stacks every token step); variant B unrolls (round 4)
    variants = [
        ("scan-layers (round-3 shipped)",
         decode_config(base, unroll_layers=False), False),
        ("unrolled layers", decode_config(base), True),
    ]
    if quick:
        variants = variants[1:]
    for name, cfg, unroll in variants:
        time_variant(name, cfg, batch, prompt_len, new_tokens,
                     unroll_layers=unroll)

    t = decode_traffic_bytes(decode_config(base), batch)
    spec_roofline = 819e9 / t["total"] * batch
    print(f"honest roofline @ spec bw: {spec_roofline:,.0f} tok/s "
          f"(weights {t['weight_bytes']/1e6:.0f}MB + kv {t['kv_bytes']/1e6:.0f}MB)")


if __name__ == "__main__":
    main()
