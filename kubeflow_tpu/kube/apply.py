"""Server-side apply: field ownership via managedFields (fieldsV1).

The apiserver's structured-merge-diff library implements apply in full
generality; this is the principled subset an envtest analog needs
(docs/wire_compat.md documents the edges):

  - every apply records EXACTLY the applied field set for its manager in
    `metadata.managedFields` (fieldsV1: `f:<field>` keys, `k:{...}` keyed
    list items with a `.` membership marker, atomic lists as leaves);
  - a field another APPLY manager owns conflicts (409) unless the applied
    value is identical (co-ownership) or `force=true` steals it;
  - fields a manager applied before but dropped from its config are
    PRUNED from the object — apply is declarative, not additive;
  - keyed lists merge per `strategicmerge.MERGE_KEYS`, so two managers
    can own different containers (or different fields of one container);
  - plain updates/patches do not participate in ownership (the real
    apiserver attributes them to an `Update` operation entry; this subset
    only arbitrates between apply managers).

Reference context: the reference's controllers use create/update/patch
(SURVEY.md §2), but kubectl >=1.22 defaults `kubectl apply` to
server-side on conflict-prone paths and GitOps tooling applies CRs with
field managers — a wire server claiming apiserver fidelity must arbitrate
them.
"""

from __future__ import annotations

import copy
import json
from typing import Iterator, Optional

from .strategicmerge import MERGE_KEYS

# metadata keys the server owns; never part of an applied field set
_SERVER_META = frozenset({
    "uid", "resourceVersion", "generation", "creationTimestamp",
    "deletionTimestamp", "managedFields", "selfLink",
})


class ApplyConflict(Exception):
    """Another field manager owns one of the applied fields."""

    def __init__(self, clashes: list[tuple[str, tuple]]):
        self.clashes = clashes  # (manager, fieldsV1 leaf path)
        details = "; ".join(
            f"{_pretty(path)} (owned by {mgr})" for mgr, path in clashes)
        super().__init__(f"conflict with other field managers: {details}")


def sanitize_applied(applied: dict) -> dict:
    """Strip server-managed fields from an applied config — clients that
    read-modify-apply send uid/resourceVersion/managedFields back, and
    none of those may be applied (status goes through its subresource)."""
    out = copy.deepcopy(applied)
    meta = out.get("metadata")
    if isinstance(meta, dict):
        for key in _SERVER_META:
            meta.pop(key, None)
    out.pop("status", None)
    return out


def _merge_key_for(field_name: str, items: list) -> Optional[str]:
    candidates = MERGE_KEYS.get(field_name)
    if not candidates:
        return None
    dict_items = [x for x in items if isinstance(x, dict)]
    if not dict_items or len(dict_items) != len(items):
        return None
    for cand in candidates:
        if all(cand in x for x in dict_items):
            return cand
    return None


def field_set(obj: dict) -> dict:
    """fieldsV1 tree of an applied config.  apiVersion/kind and
    server-managed metadata are excluded (the server owns them).  An
    applied EMPTY map claims nothing — `spec: {}` must neither conflict
    with other managers' spec fields nor own the subtree atomically."""
    out: dict = {}
    for key, val in obj.items():
        if key in ("apiVersion", "kind", "status"):
            continue
        if key == "metadata" and isinstance(val, dict):
            meta = {k: v for k, v in val.items() if k not in _SERVER_META
                    and k not in ("name", "namespace")}
            _fs_add(out, "metadata", meta, None)
            continue
        _fs_add(out, key, val, key)
    return out


def _fs_add(out: dict, key: str, val, field_name: Optional[str]) -> None:
    sub = _fs_value(val, field_name)
    if isinstance(val, dict) and not sub:
        return  # empty maps (transitively) claim nothing
    out[f"f:{key}"] = sub


def _fs_value(val, field_name: Optional[str]) -> dict:
    if isinstance(val, dict):
        out: dict = {}
        for k, v in val.items():
            _fs_add(out, k, v, k)
        return out
    if isinstance(val, list):
        key = _merge_key_for(field_name or "", val)
        if key is None:
            return {}  # atomic list: owned wholesale
        out = {}
        for item in val:
            tok = "k:" + json.dumps({key: item[key]}, sort_keys=True,
                                    separators=(",", ":"))
            entry: dict = {}
            for k, v in item.items():
                _fs_add(entry, k, v, k)
            entry["."] = {}
            out[tok] = entry
        return out
    return {}  # scalar leaf


def leaf_paths(fs: dict, prefix: tuple = ()) -> Iterator[tuple]:
    """Ownable leaves of a fieldsV1 tree.  A `k:` item's `.` marker is a
    leaf (item membership); empty dicts are value leaves.  Tolerates
    malformed trees (clients can write arbitrary managedFields through
    plain create/update): non-dict nodes are leaves."""
    for key, sub in fs.items():
        if not isinstance(key, str):
            continue
        path = prefix + (key,)
        if not isinstance(sub, dict) or not sub:
            yield path
        else:
            yield from leaf_paths(sub, path)


def _contains_path(fs: dict, path: tuple) -> bool:
    cur = fs
    for tok in path:
        if not isinstance(cur, dict) or tok not in cur:
            return False
        cur = cur[tok]
    return True


def _value_at(obj: dict, path: tuple):
    """Object value addressed by a fieldsV1 leaf path; _MISSING if absent."""
    cur: object = obj
    for tok in path:
        if tok == ".":
            continue  # membership marker: the item itself
        if tok.startswith("f:"):
            if not isinstance(cur, dict):
                return _MISSING
            if tok[2:] not in cur:
                return _MISSING
            cur = cur[tok[2:]]
        elif tok.startswith("k:"):
            if not isinstance(cur, list):
                return _MISSING
            want = json.loads(tok[2:])
            for item in cur:
                if isinstance(item, dict) and all(
                        item.get(k) == v for k, v in want.items()):
                    cur = item
                    break
            else:
                return _MISSING
        else:  # pragma: no cover — unknown token kind
            return _MISSING
    return cur


class _Missing:
    pass


_MISSING = _Missing()


def find_conflicts(
    applied: dict, applied_fs: dict, current: dict,
    others: list[tuple[str, dict]],
) -> list[tuple[str, tuple]]:
    """(manager, leaf path) for every applied leaf another manager owns
    with a DIFFERENT current value — equal values co-own, no conflict."""
    clashes: list[tuple[str, tuple]] = []
    for path in leaf_paths(applied_fs):
        if path[-1] == ".":
            # item MEMBERSHIP always co-owns: two managers applying
            # disjoint field subsets of the same container must compose,
            # not 409 (conflicts arise only on actual value leaves)
            continue
        desired = _value_at(applied, path)
        have = _value_at(current, path)
        if desired is not _MISSING and have is not _MISSING \
                and desired == have:
            continue
        for manager, fs in others:
            if _contains_path(fs, path):
                clashes.append((manager, path))
    return clashes


def _pretty(path: tuple) -> str:
    return ".".join(t[2:] if t.startswith(("f:", "k:")) else t
                    for t in path if t != ".")


def prune(obj: dict, old_fs: dict, new_fs: dict,
          others: list[tuple[str, dict]]) -> dict:
    """Remove leaves this manager owned before but no longer applies —
    unless another manager also owns them (co-ownership keeps them).

    Item-membership markers (`.`) are processed FIRST: dropping an item
    removes the whole list element (provided nobody else owns anything
    under it) — field-by-field pruning first would strip the merge key
    and strand an unidentifiable empty item."""
    out = copy.deepcopy(obj)
    ordered = sorted(leaf_paths(old_fs),
                     key=lambda p: 0 if p[-1] == "." else 1)
    for path in ordered:
        if _contains_path(new_fs, path):
            continue
        if path[-1] == ".":
            item = path[:-1]
            if _contains_path(new_fs, item) or any(
                    _contains_path(fs, item) for _, fs in others):
                continue  # someone still owns (part of) the item
            _remove_at(out, item)
            continue
        if any(_contains_path(fs, path) for _, fs in others):
            continue
        _remove_at(out, path)
    return out


def _remove_at(obj, path: tuple) -> None:
    if not path:
        return
    *parents, last = path
    # walk to the parent (mirrors _value_at but keeps the reference)
    cur: object = obj
    for tok in parents:
        if tok == ".":
            continue
        if tok.startswith("f:"):
            if not isinstance(cur, dict) or tok[2:] not in cur:
                return
            cur = cur[tok[2:]]
        elif tok.startswith("k:"):
            if not isinstance(cur, list):
                return
            want = json.loads(tok[2:])
            for item in cur:
                if isinstance(item, dict) and all(
                        item.get(k) == v for k, v in want.items()):
                    cur = item
                    break
            else:
                return
    if last == ".":
        return  # membership markers are pruned via their item fields
    if last.startswith("f:") and isinstance(cur, dict):
        cur.pop(last[2:], None)
    elif last.startswith("k:") and isinstance(cur, list):
        want = json.loads(last[2:])
        cur[:] = [x for x in cur if not (
            isinstance(x, dict)
            and all(x.get(k) == v for k, v in want.items()))]


def merge_applied(current: dict, applied: dict) -> dict:
    """Overlay the applied config onto the (already pruned) object —
    structural merge with keyed-list item merge; atomic lists and scalars
    replace."""
    out = copy.deepcopy(current)
    _merge_into(out, applied, None)
    return out


def _merge_into(out: dict, applied: dict, _field: Optional[str]) -> None:
    for key, val in applied.items():
        if isinstance(val, dict) and isinstance(out.get(key), dict):
            _merge_into(out[key], val, key)
        elif isinstance(val, list) and isinstance(out.get(key), list):
            mk = _merge_key_for(key, val)
            if mk is None:
                out[key] = copy.deepcopy(val)
                continue
            for item in val:
                for i, existing in enumerate(out[key]):
                    if isinstance(existing, dict) \
                            and existing.get(mk) == item[mk]:
                        merged = copy.deepcopy(existing)
                        _merge_into(merged, item, key)
                        out[key][i] = merged
                        break
                else:
                    out[key].append(copy.deepcopy(item))
        else:
            out[key] = copy.deepcopy(val)


def drop_empty_structures(obj, fs_root: dict, path: tuple = ()):  # noqa: ANN001
    """After pruning, empty dicts nobody owns disappear (the apiserver's
    structured-merge-diff does the same cleanup) — including maps emptied
    INSIDE keyed-list items (resources.limits pruned out of a container)."""
    if isinstance(obj, dict):
        for key in list(obj):
            child = obj[key]
            drop_empty_structures(child, fs_root, path + (f"f:{key}",))
            if isinstance(child, (dict, list)) and not child \
                    and not _contains_path(fs_root, path + (f"f:{key}",)):
                del obj[key]
    elif isinstance(obj, list):
        field_name = path[-1][2:] if path and path[-1].startswith("f:") else ""
        mk = _merge_key_for(field_name, obj)
        if mk is None:
            return  # atomic list: contents owned wholesale, not walked
        for item in obj:
            tok = "k:" + json.dumps({mk: item[mk]}, sort_keys=True,
                                    separators=(",", ":"))
            drop_empty_structures(item, fs_root, path + (tok,))


def apply_update(
    current: dict, applied: dict, manager: str, api_version: str,
    force: bool = False, now: str = "",
) -> dict:
    """One server-side apply step: conflict-check, prune, merge, and
    rewrite this manager's managedFields entry.  Returns the new object
    dict; raises ApplyConflict.  `applied` must be pre-sanitized
    (sanitize_applied) — ApiServer.apply does this once, outside its
    retry loop."""
    applied_fs = field_set(applied)
    meta = current.get("metadata") or {}
    entries = [e for e in (meta.get("managedFields") or [])
               if isinstance(e, dict)]  # non-dict junk from plain writes
    mine_entry: Optional[dict] = None
    mine_old: dict = {}
    others: list[tuple[str, dict]] = []
    for e in entries:
        if e.get("operation") != "Apply":
            continue
        fs = e.get("fieldsV1")
        if not isinstance(fs, dict):
            fs = {}  # malformed tree written via plain update: ignore
        if e.get("manager") == manager:
            mine_entry, mine_old = e, fs
        else:
            others.append((e.get("manager", "?"), fs))

    clashes = find_conflicts(applied, applied_fs, current, others)
    if clashes:
        if not force:
            raise ApplyConflict(clashes)
        # forced: stolen fields leave the losers' sets
        for _, path in clashes:
            for _, fs in others:
                _remove_fs_path(fs, path)

    pruned = prune(current, mine_old, applied_fs, others)
    out = merge_applied(pruned, applied)
    # everyone's ownership forest, for the cleanup walk
    forest: dict = {}
    for _, fs in others:
        _fs_union(forest, fs)
    _fs_union(forest, applied_fs)
    drop_empty_structures(out, forest)

    new_meta = out.setdefault("metadata", {})
    if mine_entry is not None and mine_entry.get("fieldsV1") == applied_fs:
        # unchanged field set: keep the old timestamp so an identical
        # re-apply is a byte-identical object — the store's no-op
        # suppression then skips the RV bump, and a GitOps loop
        # re-applying on a timer doesn't wake every watcher each pass
        now = mine_entry.get("time", now)
    new_entry = {
        "manager": manager,
        "operation": "Apply",
        "apiVersion": api_version,
        "fieldsType": "FieldsV1",
        "fieldsV1": applied_fs,
        **({"time": now} if now else {}),
    }
    # replace IN PLACE: filter-then-append would permute entry order, so
    # two managers alternating identical re-applies would never produce a
    # byte-identical object and would bump the RV forever
    kept: list[dict] = []
    for e in entries:
        if e is mine_entry:
            kept.append(new_entry)
        elif e.get("operation") == "Apply" and not e.get("fieldsV1"):
            continue  # emptied by a forced steal: drop the husk
        else:
            kept.append(e)
    if mine_entry is None:
        kept.append(new_entry)
    new_meta["managedFields"] = kept
    return out


def _remove_fs_path(fs: dict, path: tuple) -> None:
    if not path:
        return
    if len(path) == 1:
        fs.pop(path[0], None)
        return
    child = fs.get(path[0])
    if isinstance(child, dict):
        _remove_fs_path(child, path[1:])
        if not child:
            fs.pop(path[0], None)


def _fs_union(dst: dict, src: dict) -> None:
    for key, val in src.items():
        if key in dst and isinstance(dst[key], dict) and isinstance(val, dict):
            _fs_union(dst[key], val)
        else:
            dst[key] = copy.deepcopy(val)


__all__ = ["apply_update", "field_set", "leaf_paths", "ApplyConflict"]
