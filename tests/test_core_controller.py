"""Core NotebookReconciler tests: the analog of the reference's envtest BDD
suite (notebook_controller_bdd_test.go:32-96) plus the TPU slice paths
(SURVEY.md §7 build-plan steps 2-4)."""

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig


@pytest.fixture()
def env():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    mgr = Manager(api, clock=FakeClock())
    metrics = NotebookMetrics(api)
    rec = setup_core_controllers(mgr, CoreConfig(), metrics)
    return api, cluster, mgr, metrics, rec


def create_nb(api, mgr, name="test-nb", ns="user1", tpu=None, pod_spec=None,
              annotations=None):
    nb = Notebook.new(name, ns, tpu=tpu, pod_spec=pod_spec,
                      annotations=annotations)
    api.create(nb.obj)
    mgr.run_until_idle()
    return nb


class TestCpuPath:
    def test_sts_and_service_created(self, env):
        api, cluster, mgr, metrics, _ = env
        create_nb(api, mgr)
        sts = api.get("StatefulSet", "user1", "test-nb")
        assert sts.spec["replicas"] == 1
        assert sts.spec["serviceName"] == "test-nb"
        tmpl = sts.spec["template"]
        assert tmpl["metadata"]["labels"][C.NOTEBOOK_NAME_LABEL] == "test-nb"
        assert tmpl["metadata"]["labels"][C.WORKBENCH_LABEL] == "true"
        main = tmpl["spec"]["containers"][0]
        assert main["workingDir"] == "/home/jovyan"
        assert main["ports"][0]["containerPort"] == 8888
        assert {"name": "NB_PREFIX", "value": "/notebook/user1/test-nb"} in main["env"]
        assert tmpl["spec"]["securityContext"] == {"fsGroup": 100}
        svc = api.get("Service", "user1", "test-nb")
        assert svc.spec["ports"][0] == {
            "name": "http-notebook", "port": 80, "targetPort": 8888,
            "protocol": "TCP",
        }
        assert svc.spec["selector"] == {C.STATEFULSET_LABEL: "test-nb"}
        assert metrics.creation.value("user1") == 1

    def test_user_values_not_clobbered(self, env):
        api, cluster, mgr, _, _ = env
        pod_spec = {
            "containers": [{
                "name": "test-nb",
                "workingDir": "/custom",
                "ports": [{"containerPort": 9999, "name": "p"}],
                "env": [{"name": "NB_PREFIX", "value": "/mine"}],
            }],
            "securityContext": {"runAsUser": 1000},
        }
        create_nb(api, mgr, pod_spec=pod_spec)
        tmpl = api.get("StatefulSet", "user1", "test-nb").spec["template"]
        main = tmpl["spec"]["containers"][0]
        assert main["workingDir"] == "/custom"
        assert main["ports"][0]["containerPort"] == 9999
        assert main["env"] == [{"name": "NB_PREFIX", "value": "/mine"}]
        # user securityContext respected (no fsGroup injected over it)
        assert tmpl["spec"]["securityContext"] == {"runAsUser": 1000}
        # service targets the user port
        svc = api.get("Service", "user1", "test-nb")
        assert svc.spec["ports"][0]["targetPort"] == 9999

    def test_stop_annotation_scales_to_zero_and_back(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        assert api.get("Pod", "user1", "test-nb-0").body["status"]["phase"] == "Running"
        nb = api.get("Notebook", "user1", "test-nb")
        nb.metadata.annotations[C.STOP_ANNOTATION] = "2024-01-01T00:00:00Z"
        api.update(nb)
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "test-nb").spec["replicas"] == 0
        assert api.try_get("Pod", "user1", "test-nb-0") is None
        # un-cull
        nb = api.get("Notebook", "user1", "test-nb")
        del nb.metadata.annotations[C.STOP_ANNOTATION]
        api.update(nb)
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "test-nb").spec["replicas"] == 1
        assert api.get("Pod", "user1", "test-nb-0").body["status"]["phase"] == "Running"

    def test_status_mirrors_pod(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        nb = api.get("Notebook", "user1", "test-nb")
        status = nb.status
        assert status["readyReplicas"] == 1
        cond_types = {c["type"] for c in status["conditions"]}
        assert "Ready" in cond_types
        # containerState mirrors the container named like the CR
        assert "running" in status["containerState"]

    def test_drift_reverted(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        sts = api.get("StatefulSet", "user1", "test-nb")
        sts.spec["replicas"] = 5
        api.update(sts)
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "test-nb").spec["replicas"] == 1

    def test_recreated_on_delete(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        api.delete("Service", "user1", "test-nb")
        mgr.run_until_idle()
        assert api.get("Service", "user1", "test-nb") is not None

    def test_restart_annotation(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        pod_uid = api.get("Pod", "user1", "test-nb-0").metadata.uid
        nb = api.get("Notebook", "user1", "test-nb")
        nb.metadata.annotations[C.ANNOTATION_NOTEBOOK_RESTART] = "true"
        api.update(nb)
        mgr.run_until_idle()
        # pod recreated with a new identity, annotation cleared
        new_pod = api.get("Pod", "user1", "test-nb-0")
        assert new_pod.metadata.uid != pod_uid
        nb = api.get("Notebook", "user1", "test-nb")
        assert C.ANNOTATION_NOTEBOOK_RESTART not in nb.metadata.annotations

    def test_long_name_uses_generate_name(self, env):
        api, cluster, mgr, _, _ = env
        long_name = "n" * 60
        create_nb(api, mgr, name=long_name)
        stss = api.list("StatefulSet", namespace="user1")
        assert len(stss) == 1
        assert stss[0].name.startswith("nb-")
        assert len(stss[0].name) <= C.MAX_STATEFULSET_NAME_LENGTH
        # reconciling again must not create a second STS
        mgr.enqueue_all()
        mgr.run_until_idle()
        assert len(api.list("StatefulSet", namespace="user1")) == 1

    def test_status_write_idempotent_with_real_clock(self):
        """Re-reconciling with a ticking clock must not rewrite status
        (timestamps are preserved for unchanged conditions) — otherwise
        standalone mode hot-loops on its own status updates."""
        from kubeflow_tpu.utils.clock import Clock

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1")
        mgr = Manager(api, clock=Clock())  # real time
        setup_core_controllers(mgr, CoreConfig(), NotebookMetrics(api))
        api.create(Notebook.new("nb", "user1").obj)
        mgr.run_until_idle()
        rv = api.get("Notebook", "user1", "nb").metadata.resource_version
        mgr.enqueue_all("notebook")
        mgr.run_until_idle()
        assert api.get("Notebook", "user1", "nb").metadata.resource_version == rv

    def test_long_name_restart_and_pods_found(self, env):
        api, cluster, mgr, _, _ = env
        long_name = "n" * 60
        create_nb(api, mgr, name=long_name)
        sts = api.list("StatefulSet", namespace="user1")[0]
        pod_name = f"{sts.name}-0"
        pod_uid = api.get("Pod", "user1", pod_name).metadata.uid
        nb = api.get("Notebook", "user1", long_name)
        nb.metadata.annotations[C.ANNOTATION_NOTEBOOK_RESTART] = "true"
        api.update(nb)
        mgr.run_until_idle()
        assert api.get("Pod", "user1", pod_name).metadata.uid != pod_uid

    def test_terminating_notebook_not_reconciled(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        nb = api.get("Notebook", "user1", "test-nb")
        nb.metadata.finalizers = ["some/finalizer"]
        api.update(nb)
        mgr.run_until_idle()
        api.delete("Notebook", "user1", "test-nb")  # sets deletionTimestamp
        api.delete("Service", "user1", "test-nb")
        mgr.run_until_idle()
        # controller must NOT recreate while terminating
        assert api.try_get("Service", "user1", "test-nb") is None


class TestTpuPath:
    def test_v5e16_multihost_slice(self, env):
        """BASELINE config #4: v5e-16 -> 4-worker indexed STS + headless svc
        + distributed env wiring."""
        api, cluster, mgr, _, _ = env
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        create_nb(api, mgr, name="maxtext", tpu=TPUSpec("v5e", "4x4"))
        sts = api.get("StatefulSet", "user1", "maxtext")
        assert sts.spec["replicas"] == 4
        assert sts.spec["podManagementPolicy"] == "Parallel"
        assert sts.spec["serviceName"] == "maxtext-workers"
        spec = sts.spec["template"]["spec"]
        assert spec["nodeSelector"] == {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
        }
        main = spec["containers"][0]
        assert main["resources"]["requests"]["google.com/tpu"] == "4"
        assert main["resources"]["limits"]["google.com/tpu"] == "4"
        env_by_name = {e["name"]: e for e in main["env"]}
        assert env_by_name["TPU_WORKER_HOSTNAMES"]["value"] == ",".join(
            f"maxtext-{i}.maxtext-workers" for i in range(4)
        )
        assert (
            env_by_name["TPU_WORKER_ID"]["valueFrom"]["fieldRef"]["fieldPath"]
            == "metadata.labels['apps.kubernetes.io/pod-index']"
        )
        assert env_by_name["JAX_COORDINATOR_ADDRESS"]["value"] == (
            "maxtext-0.maxtext-workers:8471"
        )
        assert "MEGASCALE_NUM_SLICES" not in env_by_name  # single slice
        # headless service exists and fronts all workers
        headless = api.get("Service", "user1", "maxtext-workers")
        assert headless.spec["clusterIP"] == "None"
        assert headless.spec["selector"] == {C.NOTEBOOK_NAME_LABEL: "maxtext"}
        # all 4 workers scheduled and running on distinct TPU nodes
        pods = api.list("Pod", namespace="user1")
        assert len(pods) == 4
        assert all(p.body["status"]["phase"] == "Running" for p in pods)
        assert len({p.spec["nodeName"] for p in pods}) == 4
        # status: per-worker states + slice health
        nb = api.get("Notebook", "user1", "maxtext")
        assert nb.status["readyReplicas"] == 4
        assert nb.status["sliceHealth"] == "Healthy"
        assert len(nb.status["workerStates"]) == 4

    def test_multislice_dcn_env(self, env):
        """BASELINE config #5: 2 slices -> 2 STS + MEGASCALE_* coordination."""
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr, name="gemma", tpu=TPUSpec("v5p", "2x2x2", slices=2))
        sts0 = api.get("StatefulSet", "user1", "gemma-slice-0")
        sts1 = api.get("StatefulSet", "user1", "gemma-slice-1")
        for slice_id, sts in ((0, sts0), (1, sts1)):
            assert sts.spec["replicas"] == 2
            env_by_name = {
                e["name"]: e
                for e in sts.spec["template"]["spec"]["containers"][0]["env"]
            }
            assert env_by_name["MEGASCALE_NUM_SLICES"]["value"] == "2"
            assert env_by_name["MEGASCALE_SLICE_ID"]["value"] == str(slice_id)
            assert env_by_name["MEGASCALE_COORDINATOR_ADDRESS"]["value"] == (
                "gemma-slice-0-0.gemma-workers"
            )
        # scale-in to 1 slice prunes slice-1
        nb = api.get("Notebook", "user1", "gemma")
        nb.spec["tpu"]["slices"] = 1
        api.update(nb)
        mgr.run_until_idle()
        assert api.try_get("StatefulSet", "user1", "gemma-slice-1") is None
        assert api.get("StatefulSet", "user1", "gemma") is not None

    def test_slice_atomic_stop(self, env):
        api, cluster, mgr, _, _ = env
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        create_nb(api, mgr, name="maxtext", tpu=TPUSpec("v5e", "4x4"))
        nb = api.get("Notebook", "user1", "maxtext")
        nb.metadata.annotations[C.STOP_ANNOTATION] = "now"
        api.update(nb)
        mgr.run_until_idle()
        # whole slice gone, not partial
        assert api.get("StatefulSet", "user1", "maxtext").spec["replicas"] == 0
        assert api.list("Pod", namespace="user1") == []
        nb = api.get("Notebook", "user1", "maxtext")
        assert nb.status["sliceHealth"] == "Stopped"

    def test_degraded_slice_health(self):
        # self-healing off: this test pins the STATUS classification of a
        # partially failed slice (with healing on, the failed worker is
        # slice-restarted before the Degraded state can be observed —
        # that path is covered in tests/test_selfheal.py)
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        mgr = Manager(api, clock=FakeClock())
        setup_core_controllers(
            mgr, CoreConfig(enable_self_healing=False), NotebookMetrics(api))
        create_nb(api, mgr, name="maxtext", tpu=TPUSpec("v5e", "4x4"))
        cluster.fail_pod("user1", "maxtext-2")
        mgr.run_until_idle()
        nb = api.get("Notebook", "user1", "maxtext")
        assert nb.status["sliceHealth"] == "Degraded"
        states = {w["pod"]: w for w in nb.status["workerStates"]}
        assert states["maxtext-2"]["ready"] is False
        assert states["maxtext-2"]["phase"] == "Failed"

    def test_invalid_topology_rejected(self, env):
        from kubeflow_tpu.kube import InvalidError
        with pytest.raises(InvalidError):
            TPUSpec("v5e", "3x5x7").validate()


class TestEventReemission:
    def test_pod_event_reemitted_on_notebook(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        from kubeflow_tpu.kube import EventRecorder
        kubelet_rec = EventRecorder(api, "kubelet")
        pod = api.get("Pod", "user1", "test-nb-0")
        kubelet_rec.event(pod, "Warning", "FailedMount", "volume not found")
        mgr.run_until_idle()
        nb_events = [
            e
            for e in api.list("Event", namespace="user1")
            if e.body["involvedObject"]["kind"] == "Notebook"
        ]
        assert len(nb_events) == 1
        assert nb_events[0].body["reason"] == "FailedMount"
        assert "Reissued from pod/test-nb-0" in nb_events[0].body["message"]


class TestMetricsScrape:
    def test_running_gauge_and_chips(self, env):
        api, cluster, mgr, metrics, _ = env
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        create_nb(api, mgr, name="cpu-nb")
        create_nb(api, mgr, name="tpu-nb", tpu=TPUSpec("v5e", "4x4"))
        text = metrics.scrape()
        assert metrics.running.value("user1") == 2
        assert metrics.tpu_chips_requested.value("user1") == 16
        assert 'notebook_running{namespace="user1"} 2' in text

    def test_multislice_counts_as_one_notebook(self, env):
        api, cluster, mgr, metrics, _ = env
        create_nb(api, mgr, name="gemma", tpu=TPUSpec("v5p", "2x2x2", slices=2))
        metrics.scrape()
        assert metrics.running.value("user1") == 1
        # chips: 2 slices x 2 hosts x 4 chips
        assert metrics.tpu_chips_requested.value("user1") == 16


class TestEventReemissionCoverage:
    """Satellite coverage for EventReemitReconciler: owned StatefulSet AND
    Pod events re-emit onto the Notebook exactly once, and the UID dedup
    window holds across repeated reconciles of the same Event."""

    @staticmethod
    def _notebook_events(api, ns="user1"):
        return [
            e for e in api.list("Event", namespace=ns)
            if e.body["involvedObject"]["kind"] == "Notebook"
        ]

    def test_statefulset_event_reemitted_on_notebook(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        from kubeflow_tpu.kube import EventRecorder

        sts = api.get("StatefulSet", "user1", "test-nb")
        EventRecorder(api, "statefulset-controller").event(
            sts, "Warning", "FailedCreate", "quota exceeded")
        mgr.run_until_idle()
        nb_events = self._notebook_events(api)
        assert len(nb_events) == 1
        assert nb_events[0].body["reason"] == "FailedCreate"
        assert "Reissued from statefulset/test-nb" in \
            nb_events[0].body["message"]

    def test_reemitted_exactly_once_across_repeated_reconciles(self, env):
        """Re-reconciling the SAME Event (level-triggered re-delivery,
        resync, relist) must not re-emit: the UID dedup absorbs it, so the
        Notebook event count stays 1 (a second emission would bump the
        recorder's aggregation count)."""
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        from kubeflow_tpu.kube import EventRecorder, Request

        pod = api.get("Pod", "user1", "test-nb-0")
        src = EventRecorder(api, "kubelet").event(
            pod, "Warning", "BackOff", "restarting failed container")
        mgr.run_until_idle()
        assert len(self._notebook_events(api)) == 1

        # drive the same Event through the reconciler several more times
        for _ in range(3):
            mgr.enqueue("event-reemit", Request("user1", src.name))
            mgr.run_until_idle()
        nb_events = self._notebook_events(api)
        assert len(nb_events) == 1
        assert int(nb_events[0].body.get("count", 1)) == 1

    def test_unowned_object_event_not_reemitted(self, env):
        api, cluster, mgr, _, _ = env
        create_nb(api, mgr)
        from kubeflow_tpu.kube import EventRecorder, KubeObject, ObjectMeta

        # a pod with no notebook-name label: not ours
        stray = api.create(KubeObject(
            api_version="v1", kind="Pod",
            metadata=ObjectMeta(name="stray", namespace="user1")))
        EventRecorder(api, "kubelet").event(
            stray, "Warning", "Failed", "image pull error")
        mgr.run_until_idle()
        assert self._notebook_events(api) == []
