"""Repo-native invariant analyzers: `python -m ci.analyzers`.

The fleet-scale control plane (PRs 5-8) rests on four contracts that were
unwritten until each was violated once:

  - **clock**: all time flows through `utils/clock.py` (`Clock`), so
    FakeClock loadtests and soaks stay deterministic.  Direct
    `time.time()`/`time.monotonic()`/`datetime.now()`/`time.sleep()`
    calls outside the Clock are flagged (`clock_discipline`).
  - **cow**: objects handed out by `list()`/`list_with_rv()`/`select()`/
    `by_index()` are frozen shared snapshots; mutating one in place
    without an intervening `.deepcopy()`/`get()` is the bug class PR 8
    fixed by hand in three places (`cow_contract`).
  - **locks**: the store/cluster/cache locks nest in one global order;
    a static acquisition-order graph over `with <lock>` nesting must be
    acyclic (`lock_order`).
  - **hotpath**: reconciler/controller bodies read the InformerCache,
    never `api.list()` — O(its objects) per reconcile, not O(cluster)
    (`hot_path`).
  - **writeahead**: in the crash-resumable protocols (recovery, slice
    placement) every destructive call is dominated on the CFG by the
    status write a successor resumes from (`write_ahead`).
  - **lockset**: a field some method guards with a lock is guarded
    everywhere, with lock inheritance for private helpers (`lockset`).

Same zero-dependency ethos as `ci/lint.py`: stdlib `ast` only, runs in
the hermetic image.  Exceptions live in `allowlist.py` and every entry
carries a reason string; an entry that matches nothing fails the run
(stale exceptions are rot).  The runtime half of the gate is
`kubeflow_tpu/utils/invariants.py` (INVARIANTS_STRICT=1 deep-freeze +
lock tracking in the threaded suites); see docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent.parent
TARGETS = ["kubeflow_tpu", "tests", "ci", "conformance", "examples",
           "loadtest", "bench.py", "__graft_entry__.py"]


@dataclass
class Violation:
    check: str      # analyzer id: clock|cow|locks|hotpath|writeahead|lockset
    path: str       # repo-relative posix path ("" for project-wide)
    line: int
    context: str    # enclosing qualname (or edge/cycle descriptor)
    message: str

    def render(self) -> str:
        where = f"{self.path}:{self.line}" if self.path else "(project)"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{where}: {self.check}:{ctx} {self.message}"


@dataclass
class Module:
    """One parsed source file, shared across analyzers (parse once)."""

    path: Path
    rel: str
    src: str
    tree: ast.AST
    # lineno -> enclosing function/method qualname, filled lazily
    _qualnames: dict = field(default_factory=dict)

    def qualname_at(self, lineno: int) -> str:
        if not self._qualnames:
            self._index_qualnames()
        best = ""
        best_span = None
        for (lo, hi), name in self._qualnames.items():
            if lo <= lineno <= hi:
                span = hi - lo
                if best_span is None or span < best_span:
                    best, best_span = name, span
        return best

    def _index_qualnames(self) -> None:
        def walk(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    qn = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno)
                    if not isinstance(child, ast.ClassDef):
                        self._qualnames[(child.lineno, end)] = qn
                    walk(child, qn)
                else:
                    walk(child, prefix)

        self._qualnames[(0, 0)] = ""  # sentinel so the index is non-empty
        walk(self.tree, "")


def dotted(node) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain
    ('self.api.list'); '' for anything dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        return ""  # call in the chain: not a static path
    else:
        return ""
    return ".".join(reversed(parts))


def iter_modules() -> list[Module]:
    mods = []
    for t in TARGETS:
        p = ROOT / t
        paths = [p] if p.is_file() else sorted(p.rglob("*.py")) \
            if p.is_dir() else []
        for path in paths:
            src = path.read_text()
            rel = path.relative_to(ROOT).as_posix()
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError:
                continue  # ci/lint.py owns syntax failures
            mods.append(Module(path, rel, src, tree))
    return mods


def run_all(modules=None) -> tuple[list[Violation], dict]:
    """Run every analyzer; returns (unallowed violations, stats).
    Allowlisted violations are filtered here; allowlist entries that
    matched nothing come back as violations themselves.  `stats` carries
    per-analyzer wall time + raw finding counts under "analyzers"."""
    import time

    from . import allowlist, clock_discipline, cow_contract, hot_path, \
        lock_order, lockset, write_ahead

    if modules is None:
        modules = iter_modules()
    raw: list[Violation] = []
    timings: list[dict] = []

    def timed(check, run) -> None:
        t0 = time.perf_counter()
        found = run()
        timings.append({"check": check,
                        "seconds": round(time.perf_counter() - t0, 4),
                        "findings": len(found)})
        raw.extend(found)

    def over_modules(analyzer):
        return lambda: [v for m in modules for v in analyzer.analyze(m)]

    for analyzer in (clock_discipline, cow_contract, hot_path,
                     write_ahead, lockset):
        timed(analyzer.CHECK, over_modules(analyzer))
    timed(lock_order.CHECK, lambda: lock_order.analyze_project(modules))

    kept, allowed, stale = allowlist.apply(
        raw, scanned_paths=[m.rel for m in modules])
    kept.extend(stale)
    stats = {
        "files": len(modules),
        "violations": len(kept),
        "allowed": len(allowed),
        "analyzers": timings,
    }
    return kept, stats


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m ci.analyzers",
        description="repo-native invariant analyzers")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--json-out", metavar="FILE",
                    help="also write the JSON report to FILE (CI artifact)")
    args = ap.parse_args(argv)

    violations, stats = run_all()
    ordered = sorted(violations, key=lambda v: (v.path, v.line, v.check))
    doc = {
        "ok": not violations,
        "files": stats["files"],
        "allowed": stats["allowed"],
        "analyzers": stats["analyzers"],
        "violations": [
            {"check": v.check, "path": v.path, "line": v.line,
             "context": v.context, "message": v.message}
            for v in ordered],
    }
    if args.json_out:
        Path(args.json_out).write_text(json.dumps(doc, indent=2) + "\n")
    if args.json:
        print(json.dumps(doc, indent=2))
        return 1 if violations else 0

    for v in ordered:
        print(v.render())
    timing = "  ".join(f"{t['check']}={t['seconds']:.2f}s"
                       for t in stats["analyzers"])
    print(f"analyzers: {stats['files']} files, "
          f"{stats['violations']} violations "
          f"({stats['allowed']} allowlisted) [{timing}]")
    return 1 if violations else 0
