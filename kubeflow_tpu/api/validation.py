"""Structural (CRD-schema-level) validation for Notebook objects.

The reference gets this for free from the CRD OpenAPI schema enforced by the
apiserver (config/crd/bases); our in-memory apiserver enforces the same
contract through a validating admission hook registered at scheme setup.
Semantic ODH rules (e.g. MLflow annotation removal) stay in the ODH
validating webhook, as in the reference."""

from __future__ import annotations

from typing import Optional

from ..kube import AdmissionDenied, AdmissionHook, ApiServer, KubeObject
from .types import GROUP, KIND, VERSIONS, Notebook


def _validate(op: str, old: Optional[KubeObject], obj: KubeObject) -> None:
    group, _, version = obj.api_version.partition("/")
    if group != GROUP or version not in VERSIONS:
        raise AdmissionDenied(
            f"Notebook apiVersion {obj.api_version!r} not served; "
            f"expected {GROUP}/{{{ '|'.join(VERSIONS) }}}"
        )
    nb = Notebook(obj)
    try:
        nb.validate()
    except Exception as e:
        raise AdmissionDenied(f"invalid Notebook: {e}") from None


def install_notebook_schema(api: ApiServer) -> None:
    """Register the Notebook 'CRD': structural validation on create/update."""
    api.register_admission(
        AdmissionHook(
            kinds=(KIND,),
            handler=_validate,
            operations=("CREATE", "UPDATE"),
            mutating=False,
            name="notebook-crd-schema",
        )
    )
