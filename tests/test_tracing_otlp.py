"""OTLP/HTTP trace export: spans leave the process in collector format.

The reference's webhook tracing is real OpenTelemetry with a pluggable
provider (odh notebook_mutating_webhook.go:74-76, opentelemetry_test.go:
26-78); this verifies our OtlpHttpExporter speaks the OTLP/HTTP JSON wire
format (POST /v1/traces, ExportTraceServiceRequest) against a live local
collector socket, with trace/span-id propagation and attribute encoding.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.tracing import OtlpHttpExporter, get_tracer


class _Collector(BaseHTTPRequestHandler):
    requests: list = []

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length))
        type(self).requests.append((self.path, body))
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *args):
        pass


@pytest.fixture()
def collector():
    handler = type("Handler", (_Collector,), {"requests": []})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield url, handler
    httpd.shutdown()
    httpd.server_close()


def test_spans_reach_collector_in_otlp_format(collector):
    url, handler = collector
    exporter = OtlpHttpExporter(url, service_name="test-svc",
                                flush_interval_s=30)
    tracing.set_exporter(exporter)
    try:
        tracer = get_tracer("t")
        with tracer.start_span("admission", {"notebook": "wb", "retries": 2,
                                             "ok": True}) as root:
            root.add_event("IMAGE_STREAM_NOT_FOUND_EVENT", {"image": "x"})
            with tracer.start_span("maybeRestartRunningNotebook"):
                pass
        exporter.shutdown()
    finally:
        tracing.set_exporter(None)

    assert handler.requests, "no OTLP request received"
    path, body = handler.requests[0]
    assert path == "/v1/traces"
    rs = body["resourceSpans"][0]
    svc = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
    assert svc["service.name"] == {"stringValue": "test-svc"}
    spans = {s["name"]: s for s in rs["scopeSpans"][0]["spans"]}
    assert set(spans) == {"admission", "maybeRestartRunningNotebook"}
    root = spans["admission"]
    child = spans["maybeRestartRunningNotebook"]
    assert len(root["traceId"]) == 32 and len(root["spanId"]) == 16
    assert child["traceId"] == root["traceId"]  # same trace
    assert child["parentSpanId"] == root["spanId"]
    assert "parentSpanId" not in root
    attrs = {a["key"]: a["value"] for a in root["attributes"]}
    assert attrs["notebook"] == {"stringValue": "wb"}
    assert attrs["retries"] == {"intValue": "2"}
    assert attrs["ok"] == {"boolValue": True}
    assert root["events"][0]["name"] == "IMAGE_STREAM_NOT_FOUND_EVENT"
    assert int(root["endTimeUnixNano"]) >= int(root["startTimeUnixNano"])


def test_export_failure_never_raises():
    exporter = OtlpHttpExporter("http://127.0.0.1:1",  # nothing listens
                                flush_interval_s=30, timeout_s=0.5)
    tracing.set_exporter(exporter)
    try:
        with get_tracer("t").start_span("doomed"):
            pass
        exporter.shutdown()  # flush hits a dead socket; must not raise
    finally:
        tracing.set_exporter(None)


def test_env_setup_noop_without_endpoint():
    from kubeflow_tpu.utils.tracing import TailSampler, setup_exporter_from_env

    assert setup_exporter_from_env({}) is None
    # default: the OTLP exporter is wrapped in the tail sampler, with the
    # policy knobs read from the environment
    sampler = setup_exporter_from_env(
        {"OTEL_EXPORTER_OTLP_ENDPOINT": "http://127.0.0.1:1",
         "OTEL_SERVICE_NAME": "svc-x",
         "TRACE_TAIL_SLOW_THRESHOLD_S": "2.5",
         "TRACE_TAIL_SAMPLE_RATE": "0.25"})
    try:
        assert isinstance(sampler, TailSampler)
        assert sampler.slow_threshold_s == 2.5
        assert sampler.sample_rate == 0.25
        assert sampler.exporter.service_name == "svc-x"
        assert sampler.exporter.url.endswith("/v1/traces")
    finally:
        sampler.shutdown()
        tracing.set_exporter(None)
    # opt-out restores unconditional per-span export
    exporter = setup_exporter_from_env(
        {"OTEL_EXPORTER_OTLP_ENDPOINT": "http://127.0.0.1:1",
         "TRACE_TAIL_SAMPLING": "false"})
    try:
        assert not isinstance(exporter, TailSampler)
        assert exporter.url.endswith("/v1/traces")
    finally:
        exporter.shutdown()
        tracing.set_exporter(None)
