"""Lifecycle stage ledger: critical-path attribution of event->ready
wall time (utils/lifecycle.py).

The conservation contract is the spine of this suite: the ledger's
partition of [cause_ts, ready_ts] must sum EXACTLY to the measured wall
time — stages never overlap, never double-count, and never leak across
retries, manager failover, shard handoff, or post-ready recover/migrate
excursions.  Tests drive the ledger two ways: synthetic span trees built
on the FakeClock (every boundary controlled to the microsecond), and the
real Manager + controllers end-to-end (the feed path production runs)."""

from __future__ import annotations

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig
from kubeflow_tpu.utils.flightrecorder import FlightRecorder
from kubeflow_tpu.utils.lifecycle import (
    STAGES,
    LifecycleLedger,
    register_lifecycle_metrics,
)
from kubeflow_tpu.utils.metrics import Registry
from kubeflow_tpu.utils.tracing import get_tracer


@pytest.fixture()
def clock():
    c = FakeClock()
    tracing.set_clock(c)
    yield c
    tracing.set_clock(None)


class Harness:
    """Feeds a ledger the way the Manager does: one finished root span +
    its FlightRecorder AttemptRecord per reconcile attempt."""

    def __init__(self, clock):
        self.clock = clock
        self.tracer = get_tracer("lifecycle-test")
        self.recorder = FlightRecorder()
        self.ledger = LifecycleLedger()

    def attempt(self, *, controller="notebook", ns="u1", name="nb", gen=1,
                manager_id="", cause_ts=None, result="success",
                body=None):
        """Run one attempt NOW: `body(root)` executes inside the root span
        (open phase spans, add events, advance the clock), then the
        finished tree is fed to the ledger."""
        attrs = {"controller": controller, "namespace": ns, "name": name,
                 "generation": gen}
        if cause_ts is not None:
            attrs["cause_ts"] = cause_ts
        with self.tracer.start_span("reconcile", attrs) as root:
            if body is not None:
                body(root)
            root.set_attribute("reconcile.result", result)
        rec = self.recorder.record(root)
        self.ledger.observe_attempt(rec, root, manager_id)
        return root

    def phase(self, phase, seconds, events=()):
        """A body step: one phase child span spanning `seconds`, with
        optional (event_name, attrs) pairs added inside it."""
        with self.tracer.start_span(phase, {"phase": phase}) as span:
            for ev_name, ev_attrs in events:
                span.add_event(ev_name, ev_attrs)
            self.clock.advance(seconds)

    def entry(self, ns="u1", name="nb", gen=1):
        return self.ledger.entry(ns, name, gen)


def assert_conserved(entry):
    """The falsifiability check, exact: the stage partition sums to the
    measured wall time and no stage is negative."""
    assert entry["finalized"]
    assert all(d >= 0.0 for d in entry["stages"].values()), entry["stages"]
    assert sum(entry["stages"].values()) == pytest.approx(
        entry["wall_s"], abs=1e-9), entry


class TestConservingPartition:
    def test_single_attempt_partitions_exactly(self, clock):
        h = Harness(clock)
        cause = clock.now()
        clock.advance(3.0)  # sat in the workqueue

        def body(root):
            h.phase("render", 0.5)
            h.phase("apply", 1.0)
            clock.advance(0.25)  # un-phased reconcile work
            root.add_event("notebook.ready", {"seconds": 4.75})

        h.attempt(cause_ts=cause, body=body)
        e = h.entry()
        assert_conserved(e)
        assert e["wall_s"] == pytest.approx(4.75)
        assert e["stages"]["queue_wait"] == pytest.approx(3.0)
        assert e["stages"]["render"] == pytest.approx(0.5)
        assert e["stages"]["apply"] == pytest.approx(1.0)
        assert e["stages"]["reconcile_other"] == pytest.approx(0.25)
        cons = h.ledger.conservation()
        assert cons["finalized"] == 1 and cons["violations"] == 0
        assert cons["max_rel_err"] == 0.0

    def test_retry_gap_is_backoff_never_double_counted(self, clock):
        h = Harness(clock)
        cause = clock.now()

        h.attempt(cause_ts=cause, result="error",
                  body=lambda root: h.phase("render", 0.5))
        clock.advance(2.0)  # backoff between attempts
        h.attempt(cause_ts=cause, result="error",
                  body=lambda root: h.phase("render", 0.5))
        clock.advance(4.0)  # second, longer backoff

        def final(root):
            h.phase("render", 0.5)
            h.phase("apply", 1.0)
            root.add_event("notebook.ready", {})

        h.attempt(cause_ts=cause, body=final)
        e = h.entry()
        assert_conserved(e)
        # three render phases of 0.5s each: counted once apiece, not
        # re-summed per retry
        assert e["stages"]["render"] == pytest.approx(1.5)
        assert e["stages"]["retry_backoff"] == pytest.approx(6.0)
        assert e["stages"]["apply"] == pytest.approx(1.0)

    def test_pod_wait_gaps_follow_the_waiting_hint(self, clock):
        h = Harness(clock)
        cause = clock.now()

        h.attempt(cause_ts=cause, body=lambda root: root.add_event(
            "notebook.waiting", {"on": "pod_schedule", "ready": 0}))
        clock.advance(5.0)  # kube-scheduler binding the gang
        h.attempt(cause_ts=cause, body=lambda root: root.add_event(
            "notebook.waiting", {"on": "pod_start", "ready": 1}))
        clock.advance(7.0)  # image pull / container start
        h.attempt(cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        e = h.entry()
        assert_conserved(e)
        assert e["stages"]["pod_schedule"] == pytest.approx(5.0)
        assert e["stages"]["pod_start"] == pytest.approx(7.0)

    def test_warm_vs_cold_resolution(self, clock):
        h = Harness(clock)
        # cold: the scheduler's wait event marks provisioning
        cause = clock.now()
        h.attempt(controller="slice-scheduler", cause_ts=cause,
                  body=lambda root: h.phase(
                      "schedule", 0.0,
                      events=[("schedule.wait",
                               {"reason": "provisioning"})]))
        clock.advance(120.0)
        h.attempt(cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        cold = h.entry()
        assert_conserved(cold)
        assert cold["stages"]["schedule_cold"] == pytest.approx(120.0)
        assert "schedule_warm" not in cold["stages"]

        # warm: same shape, no wait event -> the pool hit path
        cause2 = clock.now()
        h.attempt(name="nb2", controller="slice-scheduler", cause_ts=cause2,
                  body=lambda root: h.phase("schedule", 0.5))
        clock.advance(1.0)
        h.attempt(name="nb2", cause_ts=cause2,
                  body=lambda root: root.add_event("notebook.ready", {}))
        warm = h.entry(name="nb2")
        assert_conserved(warm)
        # the schedule phase itself resolves warm; the idle gap after a
        # placed (non-waiting) attempt stays queue_wait
        assert warm["stages"]["schedule_warm"] == pytest.approx(0.5)
        assert warm["stages"]["queue_wait"] == pytest.approx(1.0)
        assert "schedule_cold" not in warm["stages"]

    def test_overlapping_controller_windows_are_clipped(self, clock):
        """Per-key serialization is per (controller, key): a notebook and
        a slice-scheduler attempt CAN overlap in real time.  The watermark
        sweep must clip the overlap instead of double-counting it."""
        from types import SimpleNamespace

        from kubeflow_tpu.utils.tracing import Span

        ledger = LifecycleLedger()
        t0 = 1000.0

        def feed(controller, start, end, ready_ts=None):
            root = Span(name="reconcile", attributes={
                "controller": controller, "namespace": "u1", "name": "nb",
                "generation": 1, "cause_ts": t0,
            }, start_time=start, end_time=end, trace_id="ab" * 16)
            if ready_ts is not None:
                root.events.append(
                    tracing.SpanEvent("notebook.ready", {}, ready_ts))
            rec = SimpleNamespace(start_time=start, end_time=end,
                                  trace_id=root.trace_id, result="success")
            ledger.observe_attempt(rec, root, "")

        feed("notebook", t0 + 1.0, t0 + 5.0)
        feed("slice-scheduler", t0 + 2.0, t0 + 4.0)  # inside the first
        feed("notebook", t0 + 6.0, t0 + 8.0, ready_ts=t0 + 8.0)
        e = ledger.entry("u1", "nb", 1)
        assert e["finalized"]
        # wall = 8s; the nested scheduler window must not inflate it
        assert e["wall_s"] == pytest.approx(8.0)
        assert sum(e["stages"].values()) == pytest.approx(8.0, abs=1e-9)

    def test_zero_wall_time_conserves_trivially(self, clock):
        h = Harness(clock)
        h.attempt(cause_ts=clock.now(),
                  body=lambda root: root.add_event("notebook.ready", {}))
        e = h.entry()
        assert e["finalized"] and e["wall_s"] == 0.0
        assert h.ledger.conservation()["violations"] == 0


class TestIsolationAndBounds:
    def test_generation_keying_isolates_spec_updates(self, clock):
        h = Harness(clock)
        cause = clock.now()
        clock.advance(1.0)
        h.attempt(gen=1, cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        # spec update: a new generation opens a FRESH entry
        cause2 = clock.now()
        clock.advance(2.0)
        h.attempt(gen=2, cause_ts=cause2,
                  body=lambda root: root.add_event("notebook.ready", {}))
        e1, e2 = h.entry(gen=1), h.entry(gen=2)
        assert e1["finalized"] and e2["finalized"]
        assert e1["wall_s"] == pytest.approx(1.0)
        assert e2["wall_s"] == pytest.approx(2.0)
        assert h.ledger.conservation()["finalized"] == 2

    def test_generation_falls_back_to_last_observed(self, clock):
        h = Harness(clock)
        cause = clock.now()
        h.attempt(gen=3, cause_ts=cause)
        clock.advance(1.0)
        # a stale-cache attempt without the generation attr joins the
        # latest entry instead of opening a phantom gen-1 ledger
        h.attempt(gen=0, cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        assert h.entry(gen=3)["finalized"]
        assert h.entry(gen=1) is None

    def test_untracked_controllers_are_ignored(self, clock):
        h = Harness(clock)
        h.attempt(controller="event-reemit")
        h.attempt(controller="warm-pool")
        assert h.ledger.pending_count() == 0

    def test_lru_bound_holds(self, clock):
        h = Harness(clock)
        h.ledger.max_notebooks = 4
        for i in range(10):
            h.attempt(name=f"nb-{i}")
        assert h.ledger.pending_count() == 4
        assert h.entry(name="nb-0") is None
        assert h.entry(name="nb-9") is not None

    def test_excursions_do_not_touch_conservation(self, clock):
        h = Harness(clock)
        cause = clock.now()
        clock.advance(1.0)
        h.attempt(cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        before = h.ledger.conservation()

        # post-ready self-healing: recover + migrate work lands in the
        # stage histograms but NOT in the conserved window
        h.attempt(body=lambda root: h.phase("recover", 2.5))
        h.attempt(body=lambda root: h.phase("migrate", 1.5))
        # a plain post-ready reconcile is not an excursion at all
        h.attempt(body=lambda root: h.phase("status", 0.1))

        after = h.ledger.conservation()
        assert after == before  # wall/attributed untouched
        assert h.ledger.excursions_total == 2
        ranked = {r["stage"]: r for r in h.ledger.ranking()}
        assert ranked["recover"]["total_s"] == pytest.approx(2.5)
        assert ranked["migrate"]["total_s"] == pytest.approx(1.5)
        assert "status" not in ranked or \
            ranked["status"]["total_s"] == pytest.approx(0.0)


class TestHandoffAndFailover:
    def test_manager_id_change_marks_handoff_wait(self, clock):
        h = Harness(clock)
        cause = clock.now()
        h.attempt(manager_id="shard-0", cause_ts=cause)
        clock.advance(9.0)  # dead shard's lease aging + adoption
        h.attempt(manager_id="shard-1", cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        e = h.entry()
        assert_conserved(e)
        assert e["stages"]["handoff_wait"] == pytest.approx(9.0)

    def test_same_manager_gap_is_not_handoff(self, clock):
        h = Harness(clock)
        cause = clock.now()
        h.attempt(manager_id="shard-0", cause_ts=cause)
        clock.advance(9.0)
        h.attempt(manager_id="shard-0", cause_ts=cause,
                  body=lambda root: root.add_event("notebook.ready", {}))
        e = h.entry()
        assert_conserved(e)
        assert "handoff_wait" not in e["stages"]
        assert e["stages"]["queue_wait"] == pytest.approx(9.0)

    def test_ledger_survives_manager_failover(self, clock):
        """run_bursty's failover shape: attempts from manager A, a
        replacement manager B adopting the SAME ledger mid-lifecycle —
        conservation must hold across the seam."""
        h = Harness(clock)
        cause = clock.now()
        h.attempt(cause_ts=cause, result="requeue")  # A sees it first
        clock.advance(3.0)
        # "failover": a new harness shares the ledger (fresh recorder +
        # tracer, like a fresh Manager)
        h2 = Harness(clock)
        h2.ledger = h.ledger
        h2.attempt(cause_ts=cause,
                   body=lambda root: root.add_event("notebook.ready", {}))
        e = h2.entry()
        assert_conserved(e)
        assert e["stages"]["retry_backoff"] == pytest.approx(3.0)
        assert h.ledger.conservation()["violations"] == 0


class TestReadSide:
    def test_ranking_shares_sum_to_one(self, clock):
        h = Harness(clock)
        for i in range(3):
            cause = clock.now()
            clock.advance(float(i + 1))
            h.attempt(name=f"nb-{i}", cause_ts=cause,
                      body=lambda root: root.add_event("notebook.ready", {}))
        ranking = h.ledger.ranking()
        assert ranking and ranking[0]["stage"] == "queue_wait"
        assert sum(r["share"] for r in ranking) == pytest.approx(1.0)
        assert ranking[0]["p99_s"] == pytest.approx(3.0)
        # every exported stage is in the closed vocabulary
        assert all(r["stage"] in STAGES for r in ranking)

    def test_namespace_rollup(self, clock):
        h = Harness(clock)
        for ns, wait in (("team-a", 2.0), ("team-b", 6.0)):
            cause = clock.now()
            clock.advance(wait)
            h.attempt(ns=ns, cause_ts=cause,
                      body=lambda root: root.add_event("notebook.ready", {}))
        roll = h.ledger.namespace_rollup()
        assert roll["team-a"]["ready_mean_s"] == pytest.approx(2.0)
        assert roll["team-b"]["ready_p99_s"] == pytest.approx(6.0)
        assert roll["team-b"]["stages"]["queue_wait"]["total_s"] == \
            pytest.approx(6.0)

    def test_snapshot_shape(self, clock):
        h = Harness(clock)
        h.attempt(body=lambda root: root.add_event("notebook.ready", {}))
        snap = h.ledger.snapshot()
        assert snap["stages"] == list(STAGES)
        assert snap["conservation"]["finalized"] == 1
        assert snap["violations"] == []
        assert snap["pending"] == 0
        assert "max_notebooks" in snap["bounds"]

    def test_histogram_exemplar_carries_trace_id(self, clock):
        registry = Registry()
        h = Harness(clock)
        h.ledger = LifecycleLedger(registry=registry)
        cause = clock.now()
        clock.advance(2.0)
        root = h.attempt(cause_ts=cause, body=lambda r: r.add_event(
            "notebook.ready", {}))
        hist = registry.get("notebook_stage_duration_seconds")
        ex = hist.exemplar("queue_wait")
        (labels, value), = [v for v in ex.values() if v is not None] or [
            (None, None)]
        assert labels == {"trace_id": root.trace_id}
        assert value == pytest.approx(2.0)
        # the exemplar's trace resolves in the flight recorder -- the
        # /debug/traces contract
        assert h.recorder.trace(root.trace_id) is not None

    def test_register_twice_returns_same_family(self):
        registry = Registry()
        assert register_lifecycle_metrics(registry) is \
            register_lifecycle_metrics(registry)


class TestEndToEnd:
    """The production feed path: real Manager + controllers on the
    FakeClock, the ledger fed from the reconcile loop itself."""

    def _stack(self, clock, cfg=None):
        api = ApiServer()
        cluster = FakeCluster(api)
        mgr = Manager(api, clock=clock)
        cfg = cfg or CoreConfig()
        metrics = NotebookMetrics(api, manager=mgr)
        ledger = LifecycleLedger(registry=metrics.registry)
        mgr.lifecycle = ledger
        metrics.attach_lifecycle(ledger)
        setup_core_controllers(mgr, cfg, metrics, provisioner=cluster)
        return api, cluster, mgr, metrics, ledger

    def test_cpu_notebook_finalizes_and_conserves(self, clock):
        api, cluster, mgr, metrics, ledger = self._stack(clock)
        cluster.add_node("n1", allocatable={"cpu": "64", "memory": "64Gi"})
        api.create(Notebook.new("nb-e2e", "u1").obj)
        mgr.settle(max_seconds=60)
        cons = ledger.conservation()
        assert cons["finalized"] == 1 and cons["violations"] == 0
        e = ledger.entry("u1", "nb-e2e", 1)
        assert_conserved(e)
        mgr.stop()

    def test_cold_provisioning_attributed_schedule_cold(self, clock):
        cfg = CoreConfig(enable_slice_scheduler=True)
        api, cluster, mgr, metrics, ledger = self._stack(clock, cfg)
        spec = TPUSpec(accelerator="v5e", topology="2x4", slices=1)
        api.create(Notebook.new("nb-tpu", "u1", tpu=spec).obj)
        mgr.settle(max_seconds=600)
        e = ledger.entry("u1", "nb-tpu", 1)
        assert_conserved(e)
        # the dominant stage of a cold boot is provisioning, split out
        # from warm hits exactly as /debug/criticalpath reports it
        assert e["stages"]["schedule_cold"] > 0.0
        top = max(e["stages"], key=e["stages"].get)
        assert top == "schedule_cold", e["stages"]
        # and the scrape carries the histogram family with samples
        scrape = metrics.scrape()
        assert "notebook_stage_duration_seconds_bucket" in scrape
        mgr.stop()

    def test_spec_update_opens_new_generation_entry(self, clock):
        api, cluster, mgr, metrics, ledger = self._stack(clock)
        cluster.add_node("n1", allocatable={"cpu": "64", "memory": "64Gi"})
        api.create(Notebook.new("nb-gen", "u1").obj)
        mgr.settle(max_seconds=60)
        assert ledger.entry("u1", "nb-gen", 1)["finalized"]

        live = api.get("Notebook", "u1", "nb-gen")
        live.body["spec"]["podSpec"] = {"containers": [
            {"name": "notebook", "image": "jupyter:next"}]}
        api.update(live)
        mgr.settle(max_seconds=60)
        gen = int(api.get("Notebook", "u1",
                          "nb-gen").metadata.generation or 1)
        assert gen > 1
        e2 = ledger.entry("u1", "nb-gen", gen)
        assert e2 is not None and e2["finalized"]
        assert ledger.conservation()["violations"] == 0
        mgr.stop()
