"""Slice-atomic self-healing: disruption detection + budgeted recovery.

The status computation has always *named* the failure mode — "partial
readiness is a degraded slice: collectives hang"
(notebook_controller._compute_and_write_status) — without acting on it: a
crashed worker, a preempted TPU node, or a stuck-Pending pod left a
multi-host notebook wedged until a human intervened.  This module closes
the loop, in the shape NotebookOS (arXiv:2503.20591) and ElasticNotebook
(arXiv:2309.11083) argue interactive platforms need:

- `classify_worker` turns the pod state the reconciler already lists into
  a disruption verdict: pod `Failed`, CrashLoopBackOff (container
  `waiting.reason`), node-driven deletion/preemption (dangling or unready
  `spec.nodeName`), or Pending beyond a configurable schedule deadline.
  Healthy and transient states (Running-not-yet-Ready, a pod
  mid-recreate, Pending within the deadline) must never trigger recovery.

- `RecoveryEngine` restarts the *entire affected slice* — JAX collectives
  cannot survive partial membership, so single-pod surgery is never
  correct — under a restart budget: exponential backoff between attempts
  (`RECOVERY_BACKOFF_*` knobs on CoreConfig), a capped attempt count
  within a sliding window, and a terminal `RecoveryExhausted=True`
  condition (+ Warning event) once the budget is spent, so the controller
  stops churning a permanently broken slice.

All bookkeeping (per-slice attempt timestamps, last-restart time, backoff
deadline, disruption stamp, exhaustion flag) is persisted in
`status.sliceRecovery` on the CR — controller memory holds nothing — so a
manager crash or leader failover (kube/leader.py) resumes the budget
instead of resetting it.  The bookkeeping write happens BEFORE the pod
deletes (write-ahead): a crash mid-restart can lose the restart, never
the attempt charge.
"""

from __future__ import annotations

import copy
import logging
from typing import Callable, Optional

from ..api.types import CONDITION_RECOVERY_EXHAUSTED, Notebook
from ..kube import (
    ApiServer,
    EventRecorder,
    KubeObject,
    NotFoundError,
    retry_on_conflict,
)
from ..utils import tracing
from ..utils.clock import Clock, parse_iso
from ..utils.config import CoreConfig
from . import constants as C
from .metrics import NotebookMetrics

logger = logging.getLogger("kubeflow_tpu.selfheal")

# recovery attempts open a `recover` phase span on the shared context
# stack, parenting onto the manager's per-attempt reconcile root — the
# flight recorder then shows recovery time per attempt (/debug/reconciles)
_TRACER = tracing.get_tracer("kubeflow_tpu.core.selfheal")

# Disruption classifications — a bounded set, because they label
# notebook_slice_restarts_total{reason}.
REASON_POD_FAILED = "pod-failed"
REASON_CRASH_LOOP = "crash-loop"
REASON_NODE_GONE = "node-gone"
REASON_PENDING_TIMEOUT = "pending-timeout"
# transient marker, not yet a disruption: a Pending worker becomes
# REASON_PENDING_TIMEOUT only once the schedule deadline passes
PENDING = "pending"

# event reasons (kubectl describe notebook)
EVENT_SLICE_RECOVERY = "SliceRecovery"
EVENT_RECOVERY_EXHAUSTED = "RecoveryExhausted"
EVENT_RECOVERY_RESTORED = "RecoveryRestored"


class SliceRestartError(Exception):
    """Aggregate of per-pod delete failures from a slice-atomic restart.

    Raised only after EVERY pod of the slice has been attempted — a
    transient error on one worker must not leave the rest of the slice
    untried, which is exactly the partial-restart state slice-atomicity
    forbids.  The reconcile fails with this and the manager's backoff
    retries the whole slice; a half-restarted slice is therefore never
    reported as recovered."""

    def __init__(self, errors: list[Exception], attempted: int) -> None:
        self.errors = errors
        self.attempted = attempted
        super().__init__(
            f"slice restart: {len(errors)}/{attempted} pod deletes failed; "
            f"first: {errors[0]}")


def _pod_ready(pod: KubeObject) -> bool:
    return any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in pod.body.get("status", {}).get("conditions", [])
    )


def classify_worker(pod: KubeObject, api: ApiServer,
                    node_cache: Optional[dict] = None) -> Optional[str]:
    """Classify one worker pod from the state the reconciler already sees.

    Returns a REASON_* constant for a disrupted worker, PENDING for a pod
    that is merely waiting to schedule/start (only the deadline makes that
    a disruption), or None for healthy and transient states that must NOT
    trigger recovery.  `node_cache` memoizes Node lookups across one
    engine pass (a slice's workers usually share few nodes)."""
    status = pod.body.get("status", {}) or {}
    if status.get("phase") == "Failed":
        return REASON_POD_FAILED
    for cs in status.get("containerStatuses", []) or []:
        waiting = (cs.get("state") or {}).get("waiting") or {}
        if waiting.get("reason") == "CrashLoopBackOff":
            return REASON_CRASH_LOOP
    node_name = pod.spec.get("nodeName", "")
    if node_name:
        if node_cache is not None and node_name in node_cache:
            node = node_cache[node_name]
        else:
            node = api.try_get("Node", "", node_name)
            if node_cache is not None:
                node_cache[node_name] = node
        if node is None:
            # the node object vanished under the pod: preemption or
            # scale-down, before the node controller reaped the pod
            return REASON_NODE_GONE
        node_ready = any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in node.body.get("status", {}).get("conditions", [])
        )
        if not node_ready:
            return REASON_NODE_GONE
    if status.get("phase") == "Pending":
        return PENDING
    return None


class RecoveryEngine:
    """Budgeted slice-atomic recovery, driven from the notebook reconcile.

    `maybe_recover` runs after the status pass: it classifies every worker
    of every slice, and for a disrupted slice either waits out the current
    backoff (returning a requeue-after hint), restarts the whole slice
    (write-ahead bookkeeping, then delete every pod), or — once the
    sliding-window attempt budget is spent — escalates to the terminal
    RecoveryExhausted condition and stops touching the slice until an
    operator heals it (at which point the budget resets)."""

    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        metrics: NotebookMetrics,
        recorder: EventRecorder,
        clock: Optional[Clock] = None,
        cache=None,
    ) -> None:
        self.api = api
        self.cfg = cfg
        self.metrics = metrics
        self.recorder = recorder
        self.clock = clock or Clock()
        # informer cache for detection-path reads (Notebook freshness,
        # Node health in classify_worker); writes always go live
        self.cache = cache

    # -- entry point ----------------------------------------------------------
    def maybe_recover(
        self,
        nb: Notebook,
        live_names: list[str],
        pods_of: Callable[[str], list[KubeObject]],
        restart_slice: Callable[[str], None],
    ) -> float:
        """One recovery pass; returns the requeue-after hint in seconds
        (0.0 = nothing scheduled).  `live_names` is ordered slice 0 first,
        as the reconciler builds it; `restart_slice` must delete every pod
        of the named slice's StatefulSet, aggregating errors
        (NotebookReconciler._restart_pods)."""
        tpu = nb.tpu
        if tpu is None or not self.cfg.enable_self_healing:
            return 0.0
        reader = self.cache if self.cache is not None else self.api
        live = reader.try_get("Notebook", nb.namespace, nb.name)
        if live is None or live.metadata.deletion_timestamp is not None:
            return 0.0
        status = live.body.get("status", {}) or {}
        recovery = copy.deepcopy(status.get("sliceRecovery") or {})
        prev_recovery = copy.deepcopy(recovery)

        # Culling precedence: a stop-annotated notebook (slice health
        # Stopping/Stopped) is being parked on purpose — "recovering" it
        # would fight the cull pod-for-pod.  Once fully Stopped, stale
        # bookkeeping (including an exhaustion verdict) is dropped so an
        # un-culled notebook starts with a fresh budget.
        if C.STOP_ANNOTATION in live.metadata.annotations or \
                status.get("sliceHealth") in ("Stopping", "Stopped"):
            if recovery and status.get("sliceHealth") == "Stopped":
                self._write_bookkeeping(nb, {})
            return 0.0

        # -- pass 1: pure detection (no span unless there is work) ------------
        shape = tpu.shape
        node_cache: dict[str, Optional[KubeObject]] = {}
        detections: list[tuple[int, str, list[tuple[str, str]], bool, bool]] = []
        for idx, live_name in enumerate(live_names):
            pods = sorted(pods_of(live_name), key=lambda p: p.name)
            reasons: list[tuple[str, str]] = []
            pending = False
            ready = 0
            for pod in pods:
                verdict = classify_worker(pod, reader, node_cache)
                if verdict == PENDING:
                    pending = True
                elif verdict is not None:
                    reasons.append((pod.name, verdict))
                if _pod_ready(pod):
                    ready += 1
            healthy = not reasons and not pending and ready >= shape.num_hosts
            detections.append((idx, live_name, reasons, pending, healthy))

        if not recovery and not any(
                reasons or pending
                for _, _, reasons, pending, _ in detections):
            return 0.0

        # -- pass 2: decisions, under the `recover` phase span ----------------
        now = self.clock.now()
        requeue = 0.0
        restarts: list[tuple[int, str, str, str, int, float]] = []
        events: list[tuple[str, str, str]] = []
        with _TRACER.start_span(
            "recover", {"phase": "recover", "namespace": nb.namespace,
                        "notebook": nb.name}
        ) as span:
            for idx, live_name, reasons, pending, healthy in detections:
                requeue = _merge_requeue(requeue, self._slice_pass(
                    nb, idx, live_name, reasons, pending, healthy,
                    recovery, restarts, events, span, now))

            # per-slice passes mutate their state dicts in place; drop
            # entries that emptied out so the persisted bookkeeping stays
            # minimal (and the no-op status check stays meaningful)
            for key in [k for k, s in recovery.items() if not s]:
                recovery.pop(key)
            exhausted = sorted(
                k for k, s in recovery.items() if s.get("exhausted"))
            if recovery != prev_recovery:
                # write-ahead: the budget charge must survive a crash
                # between here and the pod deletes below
                self._write_bookkeeping(nb, recovery, exhausted)
            for etype, reason, message in events:
                self.recorder.event(nb.obj, etype, reason, message)

            for idx, live_name, reason, pod_name, attempt_n, delay in restarts:
                span.add_event("slice.restart", {
                    "slice": idx, "sts": live_name, "reason": reason,
                    "pod": pod_name, "attempt": attempt_n,
                    "backoff_s": delay,
                })
                self.metrics.slice_restarts.labels(
                    nb.namespace, reason).inc()
                self.recorder.event(
                    nb.obj, "Normal", EVENT_SLICE_RECOVERY,
                    "restarting slice %d (%s): %s is %s (attempt %d/%d, "
                    "next backoff %.0fs)" % (
                        idx, live_name, pod_name or "workers", reason,
                        attempt_n, self.cfg.recovery_max_attempts, delay))
                restart_slice(live_name)
        return requeue

    # -- per-slice decision ---------------------------------------------------
    def _slice_pass(self, nb, idx, live_name, reasons, pending, healthy,
                    recovery, restarts, events, span, now) -> float:
        key = str(idx)
        state = recovery.get(key, {})

        # resolve Pending into a disruption only past the schedule deadline
        reason = reasons[0][1] if reasons else None
        pod_name = reasons[0][0] if reasons else ""
        if reason is None and pending:
            since = state.get("pendingSince")
            if not since:
                state["pendingSince"] = self.clock.now_iso()
                recovery[key] = state
                return self.cfg.recovery_pending_deadline_s
            waited = now - parse_iso(since)
            if waited < self.cfg.recovery_pending_deadline_s:
                return self.cfg.recovery_pending_deadline_s - waited
            reason = REASON_PENDING_TIMEOUT
        elif not pending:
            state.pop("pendingSince", None)

        if reason is None:
            if healthy and state:
                self._slice_recovered(nb, idx, state, events, span, now)
                if state:
                    recovery[key] = state
                else:
                    recovery.pop(key, None)
            elif state:
                recovery[key] = state  # pendingSince cleanup above
            return 0.0

        # -- disrupted --------------------------------------------------------
        span.add_event("slice.disrupted", {
            "slice": idx, "sts": live_name, "reason": reason,
            "pod": pod_name,
        })
        if state.get("exhausted"):
            # terminal: the budget is spent; an operator action that turns
            # the slice Healthy again (e.g. the restart annotation after a
            # fix) resets it via _slice_recovered
            recovery[key] = state
            return 0.0
        state.setdefault("disruptedAt", self.clock.now_iso())
        state["reason"] = reason
        attempts = [t for t in state.get("attempts", [])
                    if now - parse_iso(t) < self.cfg.recovery_window_s]
        state["attempts"] = attempts

        until = state.get("backoffUntil")
        if until and now < parse_iso(until):
            remaining = parse_iso(until) - now
            span.add_event("recovery.backoff_wait", {
                "slice": idx, "remaining_s": remaining})
            recovery[key] = state
            return remaining

        if len(attempts) >= self.cfg.recovery_max_attempts:
            state["exhausted"] = True
            recovery[key] = state
            span.add_event("recovery.exhausted", {
                "slice": idx, "attempts": len(attempts), "reason": reason})
            events.append((
                "Warning", EVENT_RECOVERY_EXHAUSTED,
                "slice %d (%s) spent its restart budget (%d restarts in "
                "%.0fs) on %s; manual intervention required" % (
                    idx, live_name, len(attempts),
                    self.cfg.recovery_window_s, reason)))
            logger.error(
                "recovery exhausted for %s/%s slice %d after %d attempts "
                "(%s)", nb.namespace, nb.name, idx, len(attempts), reason)
            return 0.0

        delay = min(
            self.cfg.recovery_backoff_base_s * (2 ** len(attempts)),
            self.cfg.recovery_backoff_max_s)
        stamp = self.clock.now_iso()
        attempts.append(stamp)
        state["lastRestartTime"] = stamp
        state["backoffUntil"] = _iso_at(now + delay)
        recovery[key] = state
        restarts.append((idx, live_name, reason, pod_name, len(attempts),
                         delay))
        return delay

    def _slice_recovered(self, nb, idx, state, events, span, now) -> None:
        """Disruption over: observe the detection→Healthy latency once and
        drop the transient fields.  Attempt stamps stay and age out by the
        sliding window (the flap guard) — except after exhaustion, where a
        Healthy slice means an operator fixed it and earns a fresh
        budget."""
        if state.get("disruptedAt"):
            duration = max(now - parse_iso(state["disruptedAt"]), 0.0)
            tid = span.trace_id
            self.metrics.disruption_recovery_seconds.labels(
                nb.namespace).observe(
                    duration, exemplar={"trace_id": tid} if tid else None)
            span.add_event("recovery.healthy", {
                "slice": idx, "seconds": duration})
        if state.pop("exhausted", False):
            state.pop("attempts", None)
            state.pop("backoffUntil", None)
            events.append((
                "Normal", EVENT_RECOVERY_RESTORED,
                "slice %d is Healthy again after exhaustion; restart "
                "budget reset" % idx))
        # backoffUntil deliberately survives healing: a slice that flaps
        # (fail -> restart -> Healthy -> fail) must still wait out the
        # armed backoff before the next restart, or flapping defeats the
        # exponential spacing; it expires on its own
        for field in ("disruptedAt", "reason", "pendingSince"):
            state.pop(field, None)
        if not state.get("attempts"):
            state.pop("attempts", None)
            state.pop("lastRestartTime", None)
            state.pop("backoffUntil", None)

    # -- persistence ----------------------------------------------------------
    def _write_bookkeeping(self, nb: Notebook, recovery: dict,
                           exhausted: Optional[list[str]] = None) -> None:
        """Persist status.sliceRecovery (and the RecoveryExhausted
        condition) with conflict retry.  Runs BEFORE any pod delete of the
        same pass, so the attempt charge is crash-safe."""
        exhausted = exhausted or []

        def write() -> None:
            try:
                live = self.api.get("Notebook", nb.namespace, nb.name)
            except NotFoundError:
                return
            st = live.body.setdefault("status", {})
            if recovery:
                st["sliceRecovery"] = copy.deepcopy(recovery)
            else:
                st.pop("sliceRecovery", None)
            conds = list(st.get("conditions") or [])
            existing = next(
                (c for c in conds
                 if c.get("type") == CONDITION_RECOVERY_EXHAUSTED), None)
            if exhausted:
                if existing is None or existing.get("status") != "True":
                    conds = [c for c in conds
                             if c.get("type") != CONDITION_RECOVERY_EXHAUSTED]
                    conds.append({
                        "type": CONDITION_RECOVERY_EXHAUSTED,
                        "status": "True",
                        "reason": "RestartBudgetSpent",
                        "message": "slice(s) %s spent the restart budget "
                                   "(%d attempts within %.0fs)" % (
                                       ",".join(exhausted),
                                       self.cfg.recovery_max_attempts,
                                       self.cfg.recovery_window_s),
                        "lastTransitionTime": self.clock.now_iso(),
                    })
            elif existing is not None:
                conds = [c for c in conds
                         if c.get("type") != CONDITION_RECOVERY_EXHAUSTED]
            st["conditions"] = conds
            self.api.update_status(live)

        retry_on_conflict(write)


def _merge_requeue(current: float, hint: float) -> float:
    """Combine requeue-after hints: 0 means 'none'; otherwise soonest
    wins."""
    if hint <= 0:
        return current
    if current <= 0:
        return hint
    return min(current, hint)


def _iso_at(t: float) -> str:
    import time as _time

    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(t))


__all__ = [
    "PENDING",
    "REASON_CRASH_LOOP",
    "REASON_NODE_GONE",
    "REASON_PENDING_TIMEOUT",
    "REASON_POD_FAILED",
    "RecoveryEngine",
    "SliceRestartError",
    "classify_worker",
]
