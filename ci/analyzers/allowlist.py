"""Justified exceptions to the invariant analyzers.

Every entry names the check, where it applies, and WHY the violation is
intentional — an allowlist entry without a real reason is a bug filed
against the author.  Matching:

  - `path` is a repo-relative prefix ("tests/" covers the directory,
    "kubeflow_tpu/kube/controller.py" one file);
  - `context` matches the violation's enclosing qualname exactly, or
    "*" for any context in the path (for lock cycles, the context is the
    rendered cycle string).

Entries that match nothing fail the run: stale exceptions rot into
blanket ones.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import Violation


@dataclass(frozen=True)
class Allow:
    check: str
    path: str       # repo-relative path prefix
    context: str    # exact qualname / cycle descriptor, or "*"
    reason: str


ALLOWLIST: tuple[Allow, ...] = (
    # -- clock discipline ----------------------------------------------------
    Allow("clock", "kubeflow_tpu/utils/clock.py", "*",
          "the Clock abstraction itself — the one sanctioned home of "
          "direct time calls"),
    Allow("clock", "kubeflow_tpu/utils/tracing.py", "_now",
          "documented fallback when no clock has been pinned via "
          "set_clock(); every manager path pins one"),
    Allow("clock", "kubeflow_tpu/utils/profiler.py", "*",
          "the continuous profiler samples REAL wall time by design: a "
          "FakeClock stands still while reconciles execute, so "
          "logical-time sampling would never fire, and the self-overhead "
          "ratio must measure true elapsed wall time; tier-1 keeps the "
          "sampler off (ENABLE_CONTINUOUS_PROFILER=false) and drives "
          "sample_once()/_record() directly"),
    Allow("clock", "kubeflow_tpu/kube/controller.py", "Manager._on_event",
          "intentionally real monotonic: event-cause stamps measure true "
          "wall latency so the fleet loadtest reports real p99 "
          "event->reconcile-start even under FakeClock"),
    Allow("clock", "kubeflow_tpu/kube/controller.py", "Manager._pop",
          "pairs with the _on_event cause stamp (real wall latency "
          "observation, not control logic)"),
    Allow("clock", "kubeflow_tpu/kube/controller.py", "Manager._process_item",
          "real monotonic attempt stamps feed "
          "FlightRecorder.overlapping_attempts(), the per-key concurrency "
          "audit — logical FakeClock time would alias attempts"),
    Allow("clock", "kubeflow_tpu/kube/meta.py", "now_iso",
          "creationTimestamp stamp at store commit; the store is "
          "deliberately clockless and no control logic reads the stamp "
          "back (culling reads annotations, which flow off the Clock)"),
    Allow("clock", "kubeflow_tpu/tpu/device_plugin.py", "main",
          "real kubelet-registration daemon retry loop on a real node — "
          "there is no test timeline to keep deterministic"),
    Allow("clock", "kubeflow_tpu/models/train.py", "timed_steps",
          "measures real XLA step wall time (tokens/sec, MFU) — the "
          "measurement IS the product"),
    Allow("clock", "bench.py", "*",
          "benchmark harness: real wall time is the reported metric"),
    Allow("clock", "ci/", "*",
          "decode/MFU sweep harnesses time real device execution"),
    Allow("clock", "loadtest/", "*",
          "loadtests report real wall throughput (reconciles/sec) "
          "alongside the FakeClock logical timeline"),
    Allow("clock", "conformance/behavior.py", "wait",
          "polls a real external apiserver process for convergence"),
    Allow("clock", "examples/", "*",
          "examples drive real subprocesses/clusters and poll them on "
          "the wall clock"),
    Allow("clock", "tests/", "*",
          "wall-clock deadlines around REAL threads (leader election, "
          "wire servers, worker pools) — a FakeClock cannot advance "
          "another thread's progress; logical-time tests already inject "
          "FakeClock via fixtures"),
    Allow("clock", "kubeflow_tpu/testing/interleave.py", "*",
          "the schedule explorer's budget and wedge guards must measure "
          "TRUE wall time: they bound how long CI spends enumerating and "
          "detect threads that stopped cooperating — a logical clock "
          "would never expire while a run is wedged"),
    # -- COW / frozen contract -----------------------------------------------
    Allow("cow", "tests/test_analyzers.py", "*",
          "the sanitizer's own test suite seeds deliberate "
          "mutate-after-list violations inside pytest.raises blocks to "
          "prove strict mode raises"),
    # -- lock discipline -----------------------------------------------------
    Allow("locks", "kubeflow_tpu/kube/store.py",
          "store.<instance>.lock->store.<instance>.lock",
          "multi-shard acquisition in subscribe() takes sibling shard "
          "locks in sorted-by-kind order under _shards_lock; the runtime "
          "LockTracker enforces the rank order under INVARIANTS_STRICT"),
    # -- lockset (lock-inconsistent field access) ----------------------------
    Allow("lockset", "kubeflow_tpu/kube/cache.py", "InformerCache.connected",
          "GIL-atomic bool used for double-checked locking: "
          "ensure_connected() re-checks it under _conn_lock before "
          "reconnecting, so a stale lock-free read only costs one extra "
          "call, never a double subscribe"),
    Allow("lockset", "kubeflow_tpu/kube/cache.py", "InformerCache.drops",
          "single-writer telemetry counter bumped on the apiserver's "
          "watch-delivery thread; taking a cache lock there would nest "
          "cache locks under the store's watch fan-out, and a torn read "
          "in stats() only misstates a diagnostic count"),
    Allow("lockset", "kubeflow_tpu/kube/cluster.py",
          "FakeCluster._session_store",
          "attached once during test setup before the cluster sees "
          "concurrent traffic; read-only afterwards (the guarded sites "
          "are just reads that happen to run under _mutex)"),
    Allow("lockset", "kubeflow_tpu/kube/cluster.py", "FakeCluster.api",
          "the apiserver reference never rebinds after __init__ — "
          ".update()/.delete() mutate the store BEHIND the reference "
          "(which has its own shard locks), but the container-mutator "
          "heuristic cannot tell api.update from dict.update"),
    Allow("lockset", "kubeflow_tpu/kube/controller.py",
          "Manager._event_latency",
          "deque(maxlen) appends are GIL-atomic; the _pop sampling path "
          "deliberately records wall latency outside _lock (hot path), "
          "and the loadtest reader snapshots under _lock"),
    Allow("lockset", "kubeflow_tpu/kube/controller.py",
          "Manager._registrations",
          "register/unregister mutate the list under _lock, but the "
          "event and reconcile hot paths iterate lock-free: CPython list "
          "iteration is tear-free, and a racing (un)register only means "
          "one delivery sees the previous registration set — "
          "_process_item re-validates liveness under _lock (alive())"),
    Allow("lockset", "kubeflow_tpu/kube/controller.py",
          "Manager._trace_ids",
          "each key is owned by exactly one worker between _pop and "
          "_done (per-key serialization), so same-key get/set never "
          "interleave; the locked sites touch other keys and dict ops "
          "are GIL-atomic"),
    # -- hot-path scan ban ---------------------------------------------------
    Allow("hotpath", "kubeflow_tpu/core/scheduler.py",
          "SliceScheduler._inventory",
          "TPUWarmPool claim bookkeeping needs read-your-writes "
          "freshness for optimistic-concurrency claims, and pools are "
          "O(shapes), not O(fleet) — a cache read would retry more, "
          "not less"),
)


def apply(violations: list[Violation], scanned_paths=None
          ) -> tuple[list[Violation], list[Violation], list[Violation]]:
    """(kept, allowed, stale-entry violations).  `scanned_paths` (repo-
    relative paths actually analyzed) scopes staleness: an entry whose
    path prefix matches no scanned file targets a tree absent from this
    reduced context (the Dockerfile build copies only kubeflow_tpu+ci)
    and is skipped, not reported stale."""
    used: set[Allow] = set()
    kept: list[Violation] = []
    allowed: list[Violation] = []
    for v in violations:
        hit = None
        for entry in ALLOWLIST:
            if entry.check != v.check:
                continue
            if not v.path.startswith(entry.path):
                continue
            if entry.context != "*" and entry.context != v.context:
                continue
            hit = entry
            break
        if hit is None:
            kept.append(v)
        else:
            used.add(hit)
            allowed.append(v)
    stale = []
    for entry in ALLOWLIST:
        if entry in used:
            continue
        if scanned_paths is not None and \
                not any(p.startswith(entry.path) for p in scanned_paths):
            continue  # the entry's whole target tree was not scanned
        stale.append(Violation(
            "allowlist", entry.path, 0, entry.context,
            f"stale allowlist entry for check {entry.check!r} "
            f"(reason: {entry.reason}) matches no violation — remove it"))
    return kept, allowed, stale
