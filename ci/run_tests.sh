#!/usr/bin/env bash
# Unit + integration suite on the 8-device virtual CPU mesh
# (reference .github/workflows unit job analog), preceded by every static
# gate the environment can actually run: the hermetic linter always, and
# ruff/mypy whenever they are installed (pyproject.toml pins their
# config), so the lint/typecheck workflows enforce outside GitHub too.
set -euo pipefail
cd "$(dirname "$0")/.."

# --typecheck: the ruff+mypy gate is REQUIRED — absence fails instead of
# silently skipping (a gate that never runs is not coverage; the tools
# are vendored into the Dockerfile image).  Without the flag they still
# run opportunistically when installed.
REQUIRE_TYPECHECK=0
FILTERED=()
for a in "$@"; do
  if [[ "$a" == "--typecheck" ]]; then REQUIRE_TYPECHECK=1; else FILTERED+=("$a"); fi
done
set -- ${FILTERED+"${FILTERED[@]}"}

python ci/lint.py
# invariant analyzers (ci/analyzers): clock discipline, COW/frozen
# contract, lock-order graph, hot-path scan ban, write-ahead dominance,
# lockset race detection — zero unexplained violations; exceptions live
# in ci/analyzers/allowlist.py with reasons (docs/STATIC_ANALYSIS.md).
# The JSON report (per-analyzer findings + wall time) lands as a CI
# artifact next to the human output.
python -m ci.analyzers --json-out "${ANALYZERS_JSON_OUT:-/tmp/analyzers_report.json}"
if command -v ruff >/dev/null 2>&1; then
  RUFF="ruff"
elif python -c "import ruff" 2>/dev/null; then
  RUFF="python -m ruff"
else
  RUFF=""
fi
if [[ -n "$RUFF" ]]; then
  echo "== ruff =="
  $RUFF check kubeflow_tpu tests ci
elif [[ "$REQUIRE_TYPECHECK" == 1 ]]; then
  echo "--typecheck: ruff not installed (use the Dockerfile image)" >&2
  exit 3
fi
if python -c "import mypy" 2>/dev/null; then
  echo "== mypy =="
  python -m mypy kubeflow_tpu
elif [[ "$REQUIRE_TYPECHECK" == 1 ]]; then
  echo "--typecheck: mypy not installed (use the Dockerfile image)" >&2
  exit 3
fi
# Lanes (tests/conftest.py markers): --lane controlplane is the fast
# developer loop (~2 min, no XLA compiles of model graphs); --lane compute
# is the XLA-heavy rest; default runs everything.  --lane is accepted at
# any position; everything else passes through to pytest.
LANE=""
ARGS=()
while [[ $# -gt 0 ]]; do
  if [[ "$1" == "--lane" ]]; then
    LANE="${2:?--lane requires a value (controlplane|compute)}"; shift 2
  else
    ARGS+=("$1"); shift
  fi
done
# interleave explorer smoke budget (tests/test_interleave.py, part of
# the controlplane lane): bounded schedule enumeration keeps the
# model-checking protocol tests CI-sized (>=1000 distinct schedules
# each, seconds of wall time); ci/chaos_soak.sh INTERLEAVE_DEEP=1 raises
# these for deep exploration
export INTERLEAVE_MAX_SCHEDULES="${INTERLEAVE_MAX_SCHEDULES:-1200}"
export INTERLEAVE_BUDGET_S="${INTERLEAVE_BUDGET_S:-60}"
if [[ -n "$LANE" ]]; then
  case "$LANE" in
    controlplane|compute) ;;
    *) echo "unknown lane '$LANE' (want controlplane|compute)" >&2; exit 2 ;;
  esac
  python -m pytest tests/ -q -m "$LANE" ${ARGS+"${ARGS[@]}"}
else
  python -m pytest tests/ -q ${ARGS+"${ARGS[@]}"}
fi
# seeded chaos soaks at the CI round counts (the in-suite run above
# already did the default rounds; this prints a reproducible seed line
# and runs a deeper sweep of the fault soak, the self-healing recovery
# soak, and the replicated-kernel failover lane gated against the
# ci/fleet_budget.json "failover" promotion-p99 ceiling — all
# FakeClock-driven, seconds of wall time)
if [[ -z "$LANE" || "$LANE" == "controlplane" ]]; then
  bash ci/chaos_soak.sh
  # bench trajectory: the newest measured headline MFU must stay within
  # 10% of the best-so-far, and a skipped bench run must carry a reason —
  # the r05 silent-crash class of regression fails here now
  python ci/bench_trajectory_check.py
  # metric-family inventory vs the committed golden list — renames/removals
  # fail here instead of silently breaking dashboards
  bash ci/metrics_drift_check.sh
  # boot the standalone manager and drive the operator debug surface
  # (/debug/reconciles, /debug/workqueue, OpenMetrics negotiation) over
  # real HTTP — the flight-recorder path users actually hit
  bash ci/debug_endpoints_smoke.sh
  # perf smoke: deterministic convergence benchmark — 200 notebooks on the
  # FakeClock must converge within the committed API-verb/reconcile budget
  # (>10% regression in calls-per-notebook fails), reach a zero-write
  # steady state, and produce the identical final cluster state with 1 and
  # 8 workers (per-key serialization proven via the flight recorder)
  echo "== loadtest convergence smoke =="
  python loadtest/convergence.py --count 200 --compare-workers 8 \
    --check-budget ci/apiserver_call_budget.json
  # scheduler smoke: bursty arrival trace through the slice scheduler +
  # warm pool, warm-on vs warm-off — warm p50 notebook-ready time must
  # stay strictly (and by margin, see the budget) below the cold path,
  # with gang atomicity and pool bookkeeping audited at every wave and a
  # manager failover injected mid-run
  echo "== loadtest bursty warm-pool smoke =="
  python loadtest/convergence.py --bursty 24 --bursts 3 --warm-size 8 \
    --tpu v5e:4x4 --check-warm-budget ci/warmpool_budget.json
  # active-active gate, swept: 200 then 600 notebooks over a 3-replica
  # sharded fleet with a kill+rejoin cycle per point — each point prints
  # its per-stage critical-path table and must conserve (attributed stage
  # time == measured event->ready wall time per notebook), the largest
  # point must converge under the committed wall-clock + p99
  # event->reconcile-start ceilings with the ring balanced
  # (ci/fleet_budget.json "sharded"), zero cross-process overlapping
  # reconciles, and a zero-data-plane-write steady state; the per-point
  # attribution records land in the --out artifact
  # tenant fairness smoke: 4 namespaces of placed TPU notebooks, tenant 1
  # floods spec churn — the metering ledger must attribute the flood to
  # the exact namespace, fire exactly one deduped NoisyNeighbor Warning,
  # clear it after the flood, keep chip-second conservation at zero
  # violations, and hold the victim tenants' p99 event->reconcile under
  # the ci/fleet_budget.json "tenants" ceiling
  echo "== loadtest tenant fairness smoke =="
  python loadtest/convergence.py --tenants 4 --per-tenant 3 --noisy 1 \
    --check-budget ci/fleet_budget.json
  # tenancy adversarial smoke: a low-priority flood oversubscribes the
  # fleet past its chip quota, then a high-priority burst must land via
  # checkpoint-then-preempt — flood contained at sliceHealth=Queued,
  # benign tenants untouched, zero checkpointless teardowns, zero
  # preempted-state loss, and the burst's p99 time-to-placement under
  # the ci/fleet_budget.json "tenancy" ceiling
  echo "== loadtest tenancy priorities smoke =="
  python loadtest/convergence.py --priorities 2 --benign 2 \
    --per-tenant 2 --flood 6 --check-budget ci/fleet_budget.json
  echo "== loadtest sharded fleet sweep (3 shards) =="
  python loadtest/convergence.py --sweep 200,600 --shards 3 \
    --check-budget ci/fleet_budget.json \
    --out "${SHARD_RESULT_OUT:-/tmp/shard_fleet_sweep.json}"
  # diagnosis sweep contract: every sweep point's record names a
  # non-empty binding stage from the closed vocabulary, and the sweep
  # names the knee of the wall-time curve (ROADMAP item 1's artifact)
  python - "${SHARD_RESULT_OUT:-/tmp/shard_fleet_sweep.json}" <<'PYEOF'
import json, sys
from kubeflow_tpu.utils.lifecycle import STAGES
out = json.load(open(sys.argv[1]))
for rec in out["sweep"]:
    assert rec.get("binding_stage"), \
        f"sweep point {rec['count']} missing binding_stage"
    assert rec["binding_stage"] in STAGES, rec["binding_stage"]
knee = out["knee"]
assert knee["count"] in out["points"], knee
assert knee["binding_stage"] in STAGES, knee
print(f"sweep diagnosis: knee at {knee['count']} notebooks "
      f"(binding stage {knee['binding_stage']})")
PYEOF
  # fleet-scale sharded sweep (5 shards): the head of the 100k curve —
  # 2k then 10k notebooks over the active-active fleet with a
  # kill+rejoin cycle per point, every point gated against its committed
  # per-point sub-budget (ci/fleet_budget.json "sharded_100k" points
  # map: wall clock + p99 event->reconcile-start, plus the section's
  # ring-balance and reconciles/notebook ceilings).  The 50k/100k tail
  # of the same curve runs in ci/chaos_soak.sh behind FLEET_SCALE_DEEP=1
  # so the default lane stays minutes-sized.
  echo "== loadtest sharded fleet scale sweep (5 shards) =="
  python loadtest/convergence.py --sweep 2000,10000 --shards 5 \
    --check-budget ci/fleet_budget.json --budget-section sharded_100k \
    --out "${SHARD_SCALE_OUT:-/tmp/shard_scale_sweep.json}"
  # scale-sweep contract: each point names its binding stage, records
  # its memory + shard-map contention attribution (peak RSS, RMW
  # conflicts), holds the safety invariants the sharding tier promises
  # (zero cross-process overlaps, zero steady-state data-plane writes,
  # zero conservation violations), and the knee of the wall-time curve
  # is named
  python - "${SHARD_SCALE_OUT:-/tmp/shard_scale_sweep.json}" <<'PYEOF'
import json, sys
from kubeflow_tpu.utils.lifecycle import STAGES
out = json.load(open(sys.argv[1]))
for rec in out["sweep"]:
    n = rec["count"]
    assert rec.get("budget_ok"), f"point {n} over sharded_100k sub-budget"
    assert rec.get("binding_stage") in STAGES, rec.get("binding_stage")
    assert "peak_rss_mb" in rec, f"point {n} missing peak_rss_mb"
    assert "shard_map_rmw_conflicts" in rec, \
        f"point {n} missing shard_map_rmw_conflicts"
    assert rec["cross_process_overlaps"] == 0, f"point {n}: overlap"
    assert rec["steady_data_plane_writes"] == 0, \
        f"point {n}: steady-state data-plane writes"
    assert rec["criticalpath"]["conservation"]["violations"] == 0, \
        f"point {n}: conservation violations"
knee = out["knee"]
assert knee["count"] in out["points"], knee
assert knee["binding_stage"] in STAGES, knee
print(f"scale sweep diagnosis: knee at {knee['count']} notebooks "
      f"(binding stage {knee['binding_stage']})")
PYEOF
  # fleet-scale convergence gate: 10k notebooks must converge at the same
  # reconciles/notebook as the 200-notebook smoke (within tolerance),
  # reach a zero-write steady state, and stay under the committed
  # wall-clock + p99 event->reconcile-start ceilings (ci/fleet_budget.json).
  # The run arrives in batches so the in-process TSDB holds the p99-vs-time
  # curve, and the lifecycle ledger's conservation gate must hold for all
  # 10k notebooks (attributed stage time == event->ready wall time, <=5%).
  # On a budget failure the run re-executes under cProfile and dumps the
  # top-25 cumulative listing so the regression is diagnosable from CI
  # output alone.
  echo "== loadtest fleet convergence (10k) =="
  python loadtest/convergence.py --count 10000 \
    --check-budget ci/fleet_budget.json \
    --out "${FLEET_RESULT_OUT:-/tmp/fleet_result.json}" \
    --profile-on-fail "${FLEET_PROFILE_OUT:-/tmp/fleet_profile_top25.txt}"
fi
