"""Canonical driver: the whole stack, end to end, in one script.

Boots the standalone manager (threaded event loop), creates a multi-host TPU
notebook with auth, waits for it to become Healthy, prints the interesting
objects, then stops/resumes/deletes it.  This is the script to run after any
control-plane change:

    python examples/run_stack.py
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from kubeflow_tpu.api.types import Notebook, TPUSpec  # noqa: E402
from kubeflow_tpu.core import constants as CC  # noqa: E402
from kubeflow_tpu.main import build_manager  # noqa: E402
from kubeflow_tpu.odh import constants as OC  # noqa: E402


def wait(cond, what, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            print(f"  ok: {what}")
            return
        time.sleep(0.05)
    raise SystemExit(f"TIMEOUT: {what}")


def main() -> None:
    mgr, api, cluster, metrics = build_manager()
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
    mgr.start()
    print("== create: v5e-4x4 notebook with auth")
    nb = Notebook.new(
        "demo", "team-a", tpu=TPUSpec("v5e", "4x4"),
        annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
    )
    api.create(nb.obj)
    wait(
        lambda: api.get("Notebook", "team-a", "demo")
        .body.get("status", {}).get("sliceHealth") == "Healthy",
        "slice Healthy (4 workers)",
    )
    status = api.get("Notebook", "team-a", "demo").body["status"]
    print(json.dumps(status, indent=2)[:400])
    pod = api.get("Pod", "team-a", "demo-0")
    env = {e["name"]: e.get("value") for e in pod.spec["containers"][0]["env"]}
    print("  worker env:", {k: v for k, v in env.items() if k and v})
    route = api.list("HTTPRoute", namespace="opendatahub",
                     label_selector={"notebook-name": "demo"})[0]
    print("  route:", route.name, "->",
          route.spec["rules"][0]["backendRefs"][0])

    print("== stop (slice-atomic)")
    live = api.get("Notebook", "team-a", "demo")
    live.metadata.annotations[CC.STOP_ANNOTATION] = "manual"
    api.update(live)
    wait(lambda: api.try_get("Pod", "team-a", "demo-0") is None, "workers gone")

    print("== resume")
    live = api.get("Notebook", "team-a", "demo")
    del live.metadata.annotations[CC.STOP_ANNOTATION]
    api.update(live)
    wait(
        lambda: api.get("Notebook", "team-a", "demo")
        .body.get("status", {}).get("sliceHealth") == "Healthy",
        "slice Healthy again",
    )

    print("== delete")
    api.delete("Notebook", "team-a", "demo")
    wait(lambda: api.try_get("Notebook", "team-a", "demo") is None, "finalized")
    mgr.stop()
    print("ALL OK")


if __name__ == "__main__":
    main()
