"""Create-or-update helpers with owned-fields-only drift correction.

Port of the reconcile semantics in
components/common/reconcilehelper/util.go: create if missing, otherwise copy
only the fields this controller owns (labels, annotations, replicas, pod
template spec; Services deliberately keep their clusterIP, util.go:182) and
write back only when something actually drifted.
"""

from __future__ import annotations

import copy
import logging
from typing import Callable, Optional

from ..kube import ApiServer, KubeObject

logger = logging.getLogger("kubeflow_tpu.reconcile")

CopyFn = Callable[[KubeObject, KubeObject], bool]


def copy_statefulset_fields(desired: KubeObject, found: KubeObject) -> bool:
    """CopyStatefulSetFields (util.go:107-134): labels, annotations,
    replicas, pod template spec."""
    changed = _copy_meta(desired, found)
    if desired.spec.get("replicas") != found.spec.get("replicas"):
        found.spec["replicas"] = desired.spec.get("replicas")
        changed = True
    d_tmpl = desired.spec.get("template", {})
    f_tmpl = found.spec.setdefault("template", {})
    if d_tmpl.get("spec") != f_tmpl.get("spec"):
        f_tmpl["spec"] = copy.deepcopy(d_tmpl.get("spec"))
        changed = True
    # pod template labels ride along when replicas change (reference copies
    # them unconditionally via Template.Spec plus the label special-case at
    # notebook_controller.go:193-198; we keep them continuously consistent)
    if d_tmpl.get("metadata") != f_tmpl.get("metadata"):
        f_tmpl["metadata"] = copy.deepcopy(d_tmpl.get("metadata"))
        changed = True
    return changed


copy_deployment_fields = copy_statefulset_fields  # identical owned-field set


def copy_service_fields(desired: KubeObject, found: KubeObject) -> bool:
    """CopyServiceFields (util.go:166-197): labels, annotations, selector,
    ports — NOT the whole spec, so the allocated clusterIP survives."""
    changed = _copy_meta(desired, found)
    for field in ("selector", "ports"):
        if desired.spec.get(field) != found.spec.get(field):
            found.spec[field] = copy.deepcopy(desired.spec.get(field))
            changed = True
    return changed


def copy_spec(desired: KubeObject, found: KubeObject) -> bool:
    """CopyVirtualService-style whole-spec copy (util.go:199-219), used for
    unstructured/CRD objects (HTTPRoute, NetworkPolicy, ...)."""
    changed = _copy_meta(desired, found)
    if desired.body.get("spec") != found.body.get("spec"):
        found.body["spec"] = copy.deepcopy(desired.body.get("spec"))
        changed = True
    return changed


def copy_data(desired: KubeObject, found: KubeObject) -> bool:
    """ConfigMap/Secret drift: data (+ stringData/type for Secrets)."""
    changed = _copy_meta(desired, found)
    for field in ("data", "stringData", "type"):
        if field in desired.body and desired.body.get(field) != found.body.get(field):
            found.body[field] = copy.deepcopy(desired.body.get(field))
            changed = True
    return changed


def _copy_meta(desired: KubeObject, found: KubeObject) -> bool:
    changed = False
    # a key present in found with a different/absent desired value counts as
    # drift, and desired's maps replace found's wholesale (util.go:109-121)
    if found.metadata.labels != desired.metadata.labels:
        found.metadata.labels = dict(desired.metadata.labels)
        changed = True
    if found.metadata.annotations != desired.metadata.annotations:
        found.metadata.annotations = dict(desired.metadata.annotations)
        changed = True
    return changed


def reconcile_object(
    api: ApiServer,
    desired: KubeObject,
    copy_fn: Optional[CopyFn] = None,
    cache=None,
) -> KubeObject:
    """Create-if-missing / update-if-drifted (util.go Deployment()/Service()
    pattern).  Returns the live object.

    With `cache` (kube.InformerCache) the no-op check reads the informer
    cache instead of the apiserver — zero API calls when nothing drifted,
    which is the steady-state common case.  A stale cached RV surfaces as
    a ConflictError and the manager's backoff retries against the fresher
    cache, exactly the controller-runtime cached-client contract."""
    copy_fn = copy_fn or copy_spec
    if cache is not None:
        found = cache.get(desired.kind, desired.namespace, desired.name)
    else:
        found = api.try_get(desired.kind, desired.namespace, desired.name)
    if found is None:
        logger.info("creating %s %s/%s", desired.kind, desired.namespace, desired.name)
        return api.create(desired)
    if copy_fn(desired, found):
        logger.info("updating %s %s/%s", desired.kind, desired.namespace, desired.name)
        return api.update(found)
    return found
