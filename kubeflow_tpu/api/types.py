"""Notebook API types: the contract between users and the controllers.

Mirrors the reference CRD shape — `spec.template.spec` is a raw PodSpec
passthrough and status mirrors pod conditions + container state
(components/notebook-controller/api/v1/notebook_types.go:26-88) — extended
with the TPU-first `spec.tpu` block:

    spec:
      tpu:
        accelerator: v5e            # v4 | v5e | v5p | v6e
        topology: "4x4"             # per-generation dims
        slices: 1                   # >1 => multi-slice DCN data-parallel
      template:
        spec: {containers: [...]}   # PodSpec passthrough, as in the reference

Like the reference there are three field-identical versions (v1alpha1,
v1beta1, v1); v1 is the storage version and v1beta1 the conversion hub
(api/v1beta1/notebook_conversion.go:19, api/v1/notebook_conversion.go:25-69).
Status gains per-worker readiness and slice health for multi-host slices.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Optional

from ..kube import InvalidError, KubeObject, ObjectMeta
from ..tpu.topology import SliceShape, TopologyError, resolve

GROUP = "kubeflow.org"
KIND = "Notebook"
STORAGE_VERSION = "v1"
HUB_VERSION = "v1beta1"
VERSIONS = ("v1alpha1", "v1beta1", "v1")

# Priority classes (spec.priority): the tenancy layer's admission and
# preemption ordering (core/scheduler.py, core/preemption.py).  Rank gaps
# leave room for future classes without renumbering.  A notebook without
# spec.priority inherits its tenant's default from the TenantQuota object
# (or PRIORITY_DEFAULT when no quota is configured).
PRIORITY_RANK = {"low": 0, "standard": 100, "high": 200}
PRIORITY_CLASSES = tuple(sorted(PRIORITY_RANK, key=PRIORITY_RANK.get))
PRIORITY_DEFAULT = "standard"

# Condition types mirror pod conditions (reference PodCondToNotebookCond,
# notebook_controller.go:376-414)
CONDITION_RUNNING = "Running"
CONDITION_WAITING = "Waiting"
CONDITION_TERMINATED = "Terminated"
# TPU extension: terminal verdict of the self-healing engine — the slice
# spent its restart budget and the controller stopped recovering it
# (core/selfheal.py); cleared when the slice reads Healthy again
CONDITION_RECOVERY_EXHAUSTED = "RecoveryExhausted"


@dataclass(frozen=True)
class TPUSpec:
    accelerator: str
    topology: str
    slices: int = 1

    @classmethod
    def from_dict(cls, d: dict) -> "TPUSpec":
        return cls(
            accelerator=str(d.get("accelerator", "")),
            topology=str(d.get("topology", "")),
            slices=int(d.get("slices", 1)),
        )

    def to_dict(self) -> dict:
        return {
            "accelerator": self.accelerator,
            "topology": self.topology,
            "slices": self.slices,
        }

    def validate(self) -> SliceShape:
        if self.slices < 1:
            raise InvalidError("spec.tpu.slices must be >= 1")
        try:
            return resolve(self.accelerator, self.topology)
        except TopologyError as e:
            raise InvalidError(f"spec.tpu: {e}") from None

    @property
    def shape(self) -> SliceShape:
        return self.validate()


@dataclass(frozen=True)
class ReplicationSpec:
    """Optional `spec.replication` block: run `replicas` copies of the
    kernel gang — one primary plus replicas-1 followers continuously
    restored from the primary's checkpoint-delta stream
    (core/sessionstate.py) — so slice failure promotes a caught-up
    follower (core/selfheal.py) instead of paying a snapshot -> reschedule
    -> restore cycle.  `anti_affine` keeps replica gangs on disjoint node
    pools so one pool failure cannot take both copies (core/scheduler.py
    enforces it at placement time)."""

    replicas: int = 2
    anti_affine: bool = True

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicationSpec":
        return cls(
            replicas=int(d.get("replicas", 2)),
            anti_affine=bool(d.get("antiAffine", True)),
        )

    def to_dict(self) -> dict:
        return {"replicas": self.replicas, "antiAffine": self.anti_affine}

    def validate(self) -> None:
        if self.replicas < 2:
            raise InvalidError("spec.replication.replicas must be >= 2")
        if self.replicas > 8:
            raise InvalidError("spec.replication.replicas must be <= 8")


class Notebook:
    """Typed view over a Notebook KubeObject (any API version)."""

    def __init__(self, obj: KubeObject):
        if obj.kind != KIND:
            raise ValueError(f"not a Notebook: {obj.kind}")
        self.obj = obj

    # -- constructors ---------------------------------------------------------
    @classmethod
    def new(
        cls,
        name: str,
        namespace: str,
        pod_spec: Optional[dict] = None,
        tpu: Optional[TPUSpec] = None,
        version: str = STORAGE_VERSION,
        labels: Optional[dict] = None,
        annotations: Optional[dict] = None,
        replication: Optional[ReplicationSpec] = None,
    ) -> "Notebook":
        spec: dict = {"template": {"spec": pod_spec or {"containers": [{"name": name}]}}}
        if tpu is not None:
            spec["tpu"] = tpu.to_dict()
        if replication is not None:
            spec["replication"] = replication.to_dict()
        return cls(
            KubeObject(
                api_version=f"{GROUP}/{version}",
                kind=KIND,
                metadata=ObjectMeta(
                    name=name,
                    namespace=namespace,
                    labels=dict(labels or {}),
                    annotations=dict(annotations or {}),
                ),
                body={"spec": spec},
            )
        )

    # -- accessors ------------------------------------------------------------
    @property
    def metadata(self) -> ObjectMeta:
        return self.obj.metadata

    @property
    def name(self) -> str:
        return self.obj.name

    @property
    def namespace(self) -> str:
        return self.obj.namespace

    @property
    def version(self) -> str:
        return self.obj.api_version.split("/", 1)[1]

    @property
    def pod_spec(self) -> dict:
        return self.obj.spec.setdefault("template", {}).setdefault("spec", {})

    @property
    def tpu(self) -> Optional[TPUSpec]:
        d = self.obj.spec.get("tpu")
        return TPUSpec.from_dict(d) if d else None

    @property
    def replication(self) -> Optional["ReplicationSpec"]:
        d = self.obj.spec.get("replication")
        return ReplicationSpec.from_dict(d) if d else None

    @property
    def status(self) -> dict:
        return self.obj.status

    @property
    def priority(self) -> Optional[str]:
        """Explicit priority class, or None to defer to the tenant default."""
        p = self.obj.spec.get("priority")
        return str(p) if p is not None else None

    def validate(self) -> None:
        containers = self.pod_spec.get("containers") or []
        if not containers:
            raise InvalidError("spec.template.spec.containers must be non-empty")
        if self.priority is not None and self.priority not in PRIORITY_RANK:
            raise InvalidError(
                f"spec.priority must be one of {sorted(PRIORITY_RANK)}, "
                f"got {self.priority!r}")
        if self.tpu is not None:
            self.tpu.validate()
        if self.replication is not None:
            if self.tpu is None:
                raise InvalidError(
                    "spec.replication requires spec.tpu (replicated CPU "
                    "notebooks are not supported)")
            self.replication.validate()

    # -- conversion machinery -------------------------------------------------
    def convert_to(self, version: str) -> "Notebook":
        """Spoke -> hub -> spoke conversion.  The three versions are
        field-identical (as in the reference, where the diff between
        api/v1*/notebook_types.go is only package + markers), so conversion
        is a relabel through the hub — but routed through it so a future
        field divergence has one place to live."""
        if version not in VERSIONS:
            raise InvalidError(f"unknown Notebook version {version!r}")
        hub = self._relabel(HUB_VERSION)
        return hub._relabel(version)

    def _relabel(self, version: str) -> "Notebook":
        out = self.obj.deepcopy()
        out.api_version = f"{GROUP}/{version}"
        return Notebook(out)

    def deepcopy(self) -> "Notebook":
        return Notebook(self.obj.deepcopy())


def convert_notebook_dict(obj: dict, desired_api_version: str) -> dict:
    """Dict-level conversion for the webhook server's /convert endpoint and
    the wire apiserver's converter hook (reference: the CRD conversion
    webhook, api/v1/notebook_conversion.go:25-69).  Preserves metadata —
    uid/resourceVersion must survive conversion or optimistic concurrency
    breaks on version-crossing clients."""
    group, _, version = desired_api_version.partition("/")
    if group != GROUP or not version:
        raise InvalidError(
            f"cannot convert {obj.get('apiVersion')!r} to "
            f"{desired_api_version!r}: not a {GROUP} version")
    return Notebook(KubeObject.from_dict(obj)).convert_to(version).obj.to_dict()


def notebook_status(
    ready_replicas: int,
    conditions: list[dict],
    container_state: dict,
    worker_states: Optional[list[dict]] = None,
    slice_health: Optional[str] = None,
    slice_recovery: Optional[dict] = None,
    session_state: Optional[dict] = None,
    replication: Optional[dict] = None,
) -> dict:
    """NotebookStatus shape: reference fields (conditions/readyReplicas/
    containerState, api/v1/notebook_types.go:37-45) + TPU extensions.

    `slice_recovery` is the self-healing engine's crash-safe bookkeeping
    (status.sliceRecovery, keyed by slice id: restart attempt timestamps,
    backoff deadline, disruption stamp, exhaustion flag).  It lives on the
    CR — not in controller memory — so a manager crash or leader failover
    resumes the restart budget instead of resetting it.

    `session_state` (status.sessionState, keyed by slice id) is the
    migrate verb's write-ahead restore intent: which checkpoint generation
    the recreated slice must restore, stamped BEFORE the restart so a
    manager failover mid-migration resumes the restore instead of
    forgetting it (core/selfheal.py owns the mutations).

    `replication` (status.replication) is the replicated-kernel tier's
    authority record: the fencing epoch, the current primary replica
    index, follower catch-up freshness, and — while a promotion is in
    flight — the write-ahead promotion record.  The epoch is bumped in
    the same commit that writes the promotion record, so a demoted
    primary's writes are fenced before the new primary is named
    (core/selfheal.py owns the mutations)."""
    status = {
        "conditions": conditions,
        "readyReplicas": ready_replicas,
        "containerState": copy.deepcopy(container_state),
    }
    if worker_states is not None:
        status["workerStates"] = worker_states
    if slice_health is not None:
        status["sliceHealth"] = slice_health
    if slice_recovery:
        status["sliceRecovery"] = copy.deepcopy(slice_recovery)
    if session_state:
        status["sessionState"] = copy.deepcopy(session_state)
    if replication:
        status["replication"] = copy.deepcopy(replication)
    return status
