"""Black-box behavioral conformance: drive ANY implementation over the wire.

Speaks ONLY the Kubernetes REST protocol to a server URL — no imports from
the implementation — and certifies the externally observable Notebook
contract:

  1. CRD lifecycle: a created Notebook yields a StatefulSet named after it
     (labels `notebook-name`), a ClusterIP Service on port 80 -> 8888, and
     a status with readyReplicas + conditions.
  2. The annotation protocol: setting `kubeflow-resource-stopped` scales the
     workload to 0 replicas (slice-atomically for TPU notebooks); removing
     it restores scale; `notebooks.opendatahub.io/notebook-restart: "true"`
     is cleared by the controller after acting.
  3. TPU topology contract: `spec.tpu` renders one indexed StatefulSet per
     slice with `replicas = hosts(topology)`, a headless worker Service,
     `TPU_WORKER_HOSTNAMES`/`TPU_WORKER_ID` env and `google.com/tpu`
     resources on the worker containers.
  4. Deletion: removing the Notebook removes the rendered objects.

Usage:  python conformance/behavior.py --server http://HOST:PORT [--namespace ns]
The driver for a standalone run is conformance/run.sh, which boots the
shipped manager with --serve-api and points this script at it.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

STOP = "kubeflow-resource-stopped"
RESTART = "notebooks.opendatahub.io/notebook-restart"


class Client:
    def __init__(self, server: str, namespace: str):
        self.server = server.rstrip("/")
        self.ns = namespace

    def req(self, method, path, body=None, ctype="application/json"):
        req = urllib.request.Request(
            self.server + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": ctype}, method=method)
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                raw = resp.read()
                return resp.status, json.loads(raw) if raw else {}
        except urllib.error.HTTPError as err:
            raw = err.read()
            return err.code, json.loads(raw) if raw else {}

    def nb_path(self, name=""):
        base = f"/apis/kubeflow.org/v1/namespaces/{self.ns}/notebooks"
        return f"{base}/{name}" if name else base

    def sts(self, name):
        return self.req("GET",
                        f"/apis/apps/v1/namespaces/{self.ns}/statefulsets/{name}")

    def svc(self, name):
        return self.req("GET",
                        f"/api/v1/namespaces/{self.ns}/services/{name}")


def wait(predicate, timeout=30.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(0.25)
    raise AssertionError(f"CONFORMANCE FAIL: timed out waiting for {what}")


def check_cpu_lifecycle(c: Client) -> None:
    name = "conf-cpu"
    status, _ = c.req("POST", c.nb_path(), {
        "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
        "metadata": {"name": name},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": "workbench:latest"}]}}},
    })
    assert status == 201, f"create returned {status}"
    # 1. workload rendering
    sts = wait(lambda: c.sts(name)[1] if c.sts(name)[0] == 200 else None,
               what="StatefulSet")
    labels = sts["spec"]["template"]["metadata"]["labels"]
    assert labels.get("notebook-name") == name, labels
    assert sts["spec"]["replicas"] == 1, sts["spec"].get("replicas")
    svc = wait(lambda: c.svc(name)[1] if c.svc(name)[0] == 200 else None,
               what="Service")
    port = svc["spec"]["ports"][0]
    assert (port["port"], port["targetPort"]) == (80, 8888), port
    # status contract
    wait(lambda: "readyReplicas" in (c.req("GET", c.nb_path(name))[1]
                                     .get("status") or {}),
         what="status.readyReplicas")
    # 2. stop/resume annotation protocol
    code, live = c.req("PATCH", c.nb_path(name),
                       {"metadata": {"annotations":
                                     {STOP: "2026-01-01T00:00:00Z"}}},
                       ctype="application/merge-patch+json")
    assert code == 200, (code, live)
    wait(lambda: c.sts(name)[1].get("spec", {}).get("replicas") == 0,
         what="scale to zero on stop annotation")
    c.req("PATCH", c.nb_path(name), {"metadata": {"annotations": {STOP: None}}},
          ctype="application/merge-patch+json")
    wait(lambda: c.sts(name)[1].get("spec", {}).get("replicas") == 1,
         what="scale up on stop-annotation removal")
    # restart annotation is acted on + cleared
    c.req("PATCH", c.nb_path(name),
          {"metadata": {"annotations": {RESTART: "true"}}},
          ctype="application/merge-patch+json")
    wait(lambda: RESTART not in (c.req("GET", c.nb_path(name))[1]
                                 .get("metadata", {}).get("annotations") or {}),
         what="restart annotation cleared by controller")
    # 4. deletion
    c.req("DELETE", c.nb_path(name))
    wait(lambda: c.req("GET", c.nb_path(name))[0] == 404,
         what="notebook finalized")
    wait(lambda: c.sts(name)[0] == 404, what="StatefulSet cleanup")
    print("PASS cpu lifecycle + annotation protocol")


def check_tpu_pods_scheduled(c: Client, name: str, slices: int,
                             hosts: int) -> None:
    """Real-substrate gang check: every slice's pods must actually BIND to
    nodes — which only happens when the nodes advertise `google.com/tpu`
    allocatable (the fake device plugin on kind, real TPU nodes on GKE).
    Asserts the full gang (slices x hosts pods, hosts read off the
    observed StatefulSet replicas so the check stays black-box) is
    scheduled and that the per-pod identity env resolves ordinal order
    (TPU_WORKER_ID from the pod name, hostnames list in ordinal order)."""
    def gang():
        status, pods = c.req(
            "GET", f"/api/v1/namespaces/{c.ns}/pods"
                   f"?labelSelector=notebook-name%3D{name}")
        if status != 200:
            return None
        items = [p for p in pods.get("items", [])
                 if p["spec"].get("nodeName")]
        return items if len(items) == slices * hosts else None

    pods = wait(gang, what=f"gang of {slices * hosts} pods scheduled",
                timeout=120)
    for pod in pods:
        wb = pod["spec"]["containers"][0]
        env = {e["name"]: e for e in wb.get("env", [])}
        hostnames = env["TPU_WORKER_HOSTNAMES"]["value"].split(",")
        assert len(hostnames) == hosts, hostnames
        # ordinal order: entry i is the pod with STS ordinal i (its DNS
        # name starts "<sts>-<ordinal>."), so index == TPU_WORKER_ID
        for i, h in enumerate(hostnames):
            pod_dns = h.split(".", 1)[0]
            assert pod_dns.endswith(f"-{i}"), (i, hostnames)
    print("PASS tpu gang scheduling on real nodes")


def check_tpu_topology(c: Client, expect_scheduled: bool = False) -> None:
    name = "conf-tpu"
    slices = 2
    status, _ = c.req("POST", c.nb_path(), {
        "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
        "metadata": {"name": name},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "2x4", "slices": slices},
            "template": {"spec": {"containers": [
                {"name": name, "image": "workbench:latest"}]}},
        },
    })
    assert status == 201, f"create returned {status}"
    for i in range(slices):
        sts = wait(lambda i=i: c.sts(f"{name}-slice-{i}")[1]
                   if c.sts(f"{name}-slice-{i}")[0] == 200 else None,
                   what=f"slice-{i} StatefulSet")
        spec = sts["spec"]
        assert spec["serviceName"] == f"{name}-workers", spec.get("serviceName")
        containers = spec["template"]["spec"]["containers"]
        wb = next(ct for ct in containers if ct["name"] == name)
        env = {e["name"]: e for e in wb.get("env", [])}
        assert "TPU_WORKER_HOSTNAMES" in env, sorted(env)
        assert "TPU_WORKER_ID" in env, sorted(env)
        limits = wb.get("resources", {}).get("limits", {})
        assert "google.com/tpu" in limits, limits
    headless = wait(
        lambda: c.svc(f"{name}-workers")[1]
        if c.svc(f"{name}-workers")[0] == 200 else None,
        what="headless worker Service")
    assert headless["spec"].get("clusterIP") == "None", headless["spec"]
    if expect_scheduled:
        hosts = c.sts(f"{name}-slice-0")[1]["spec"]["replicas"]
        check_tpu_pods_scheduled(c, name, slices, hosts=hosts)
    # slice-atomic stop: ALL slices go to 0
    c.req("PATCH", c.nb_path(name),
          {"metadata": {"annotations": {STOP: "2026-01-01T00:00:00Z"}}},
          ctype="application/merge-patch+json")
    wait(lambda: all(
        c.sts(f"{name}-slice-{i}")[1].get("spec", {}).get("replicas") == 0
        for i in range(slices)), what="slice-atomic stop")
    c.req("DELETE", c.nb_path(name))
    wait(lambda: c.req("GET", c.nb_path(name))[0] == 404,
         what="tpu notebook finalized")
    wait(lambda: all(c.sts(f"{name}-slice-{i}")[0] == 404
                     for i in range(slices)),
         what="slice StatefulSet cleanup")
    print("PASS tpu topology + slice-atomic semantics")


def check_served_versions(c: Client) -> None:
    """The CRD serves v1alpha1/v1beta1/v1 with webhook conversion: a
    non-storage-version client must round-trip (each side sees its own
    apiVersion; metadata/uid shared)."""
    name = "conf-conv"
    beta = f"/apis/kubeflow.org/v1beta1/namespaces/{c.ns}/notebooks"
    status, created = c.req("POST", beta, {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": "workbench:latest"}]}}},
    })
    assert status == 201, f"v1beta1 create returned {status}: {created}"
    assert created["apiVersion"] == "kubeflow.org/v1beta1", created["apiVersion"]
    status, v1 = c.req("GET", c.nb_path(name))
    assert status == 200 and v1["apiVersion"] == "kubeflow.org/v1", \
        (status, v1.get("apiVersion"))
    assert v1["metadata"]["uid"] == created["metadata"]["uid"]
    status, lst = c.req("GET", beta)
    assert status == 200
    mine = [i for i in lst["items"] if i["metadata"]["name"] == name]
    assert mine and mine[0]["apiVersion"] == "kubeflow.org/v1beta1", lst
    c.req("DELETE", c.nb_path(name))
    wait(lambda: c.req("GET", c.nb_path(name))[0] == 404,
         what="converted notebook cleanup")
    print("PASS served-versions conversion round-trip")


def check_istio_routing(c: Client) -> None:
    """USE_ISTIO contract (reference notebook_controller.go:558-699): a
    Notebook yields a VirtualService `notebook-{ns}-{name}` whose single
    http route prefix-matches /notebook/{ns}/{name}/, rewrites to the
    same prefix by default, targets the Service on port 80 through the
    configured gateway, and is removed with the Notebook."""
    name = "conf-istio"
    vs_path = (f"/apis/networking.istio.io/v1alpha3/namespaces/{c.ns}"
               f"/virtualservices/notebook-{c.ns}-{name}")
    status, _ = c.req("POST", c.nb_path(), {
        "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
        "metadata": {"name": name},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": "workbench:latest"}]}}},
    })
    assert status == 201, f"create returned {status}"
    vs = wait(lambda: c.req("GET", vs_path)[1]
              if c.req("GET", vs_path)[0] == 200 else None,
              what="VirtualService rendered")
    spec = vs["spec"]
    assert spec.get("gateways"), spec
    (route,) = spec["http"]
    prefix = f"/notebook/{c.ns}/{name}/"
    assert route["match"] == [{"uri": {"prefix": prefix}}], route["match"]
    assert route["rewrite"] == {"uri": prefix}, route["rewrite"]
    (dest,) = route["route"]
    assert dest["destination"]["host"].startswith(f"{name}.{c.ns}.svc."), dest
    assert dest["destination"]["port"] == {"number": 80}, dest
    c.req("DELETE", c.nb_path(name))
    wait(lambda: c.req("GET", vs_path)[0] == 404,
         what="VirtualService cleanup")
    print("PASS istio VirtualService routing contract")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--server", required=True)
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--skip-tpu", action="store_true",
                        help="cluster has no TPU nodes")
    parser.add_argument("--skip-conversion", action="store_true",
                        help="CRD deployed without the conversion webhook")
    parser.add_argument("--expect-scheduled", action="store_true",
                        help="cluster has a real scheduler + TPU-capacity "
                             "nodes (fake device plugin): assert the gang "
                             "actually binds and worker env order is right")
    parser.add_argument("--istio", action="store_true",
                        help="controller runs with USE_ISTIO: assert the "
                             "VirtualService routing contract")
    args = parser.parse_args()
    c = Client(args.server, args.namespace)
    check_cpu_lifecycle(c)
    if not args.skip_conversion:
        check_served_versions(c)
    if not args.skip_tpu:
        check_tpu_topology(c, expect_scheduled=args.expect_scheduled)
    if args.istio:
        check_istio_routing(c)
    print("behavioral conformance: PASS")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as err:
        print(err)
        sys.exit(1)
