# Manager image for the kubeflow-tpu notebook controller.
# The analog of the reference's component Dockerfiles
# (components/notebook-controller/Dockerfile, odh-notebook-controller/Dockerfile):
# one process serving both reconcilers plus the admission webhooks.
#
#   docker build -t kubeflow-tpu-notebook-controller .
#   kubectl apply -f <(python -m kubeflow_tpu.deploy --profile standalone)
FROM python:3.12-slim

WORKDIR /opt/app
COPY pyproject.toml README.md ./
COPY kubeflow_tpu ./kubeflow_tpu
COPY ci ./ci
# The static gates RUN AT BUILD TIME — a type error fails the image
# build, so "the typecheck gate ran" is a property of every built image
# (ruff+mypy pinned; ci/lint.py adds the stdlib call-signature checker).
# The tools stay installed for ci/run_tests.sh --typecheck at runtime.
RUN pip install --no-cache-dir pyyaml cryptography \
        ruff==0.8.4 mypy==1.14.1 && \
    python ci/lint.py && \
    ruff check kubeflow_tpu && \
    mypy kubeflow_tpu && \
    pip install --no-cache-dir --no-deps .

# run as non-root (restricted PodSecurity), like the reference manager images
RUN useradd --uid 1001 --no-create-home controller
USER 1001

# metrics+health on 8080, admission webhooks on 9443 (serving certs are
# mounted at /tmp/k8s-webhook-server/serving-certs by the Deployment,
# matching controller-runtime's default cert-dir layout)
EXPOSE 8080 9443
ENTRYPOINT ["python", "-m", "kubeflow_tpu.main", "--in-cluster", \
            "--cert-dir", "/tmp/k8s-webhook-server/serving-certs", \
            "--enable-leader-election"]
