"""Test-wide environment: force an 8-device virtual CPU mesh.

The reference tests controllers with envtest (real apiserver, no kubelet:
components/notebook-controller/controllers/suite_test.go:50-110).  Our analog
is the in-memory API server in kubeflow_tpu.kube; for the compute plane we
emulate a TPU slice with 8 virtual CPU devices so sharding/collective code is
exercised without hardware.  Must run before the first `import jax`.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# this image's site hook re-registers the hardware PJRT plugin and overrides
# jax_platforms after env processing; pin the config explicitly so tests
# always see the 8-device virtual CPU mesh
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
