"""TPU topology math: accelerator generation + topology string -> slice shape.

This is the piece the reference has no analog for (SURVEY.md §2.5): its
workload is a hardcoded 0/1-replica StatefulSet
(notebook-controller/controllers/notebook_controller.go:434-437).  Here the
`spec.tpu` block `{accelerator, topology, slices}` determines how many hosts
a slice spans, how many chips each host exposes via the `google.com/tpu`
device plugin, and which GKE node labels
(`cloud.google.com/gke-tpu-accelerator`, `cloud.google.com/gke-tpu-topology`)
the pods must target.

Numbers follow the public GKE/Cloud TPU topology tables: v5e/v6e are 2-D
(x,y) slices with 1, 4, or 8 chips on single-host machines and 4 chips per
host in multi-host slices; v4/v5p are 3-D (x,y,z) slices with 4 chips per
host (a 2x2x1 sub-cube per host).
"""

from __future__ import annotations

import math
from dataclasses import dataclass


class TopologyError(ValueError):
    pass


@dataclass(frozen=True)
class Accelerator:
    name: str                 # user-facing: "v5e"
    gke_label: str            # cloud.google.com/gke-tpu-accelerator value
    dims: int                 # topology rank (2 for v5e/v6e, 3 for v4/v5p)
    chips_per_host: int       # chips per host in multi-host slices
    max_single_host_chips: int
    hbm_gib_per_chip: int
    bf16_peak_tflops: float   # per-chip peak, for MFU math
    hbm_gbps: float           # per-chip HBM bandwidth, for decode math


ACCELERATORS: dict[str, Accelerator] = {
    "v4": Accelerator("v4", "tpu-v4-podslice", 3, 4, 4, 32, 275.0, 1228.0),
    "v5e": Accelerator("v5e", "tpu-v5-lite-podslice", 2, 4, 8, 16, 197.0,
                       819.0),
    "v5p": Accelerator("v5p", "tpu-v5p-slice", 3, 4, 4, 95, 459.0, 2765.0),
    "v6e": Accelerator("v6e", "tpu-v6e-slice", 2, 4, 8, 32, 918.0, 1640.0),
}


@dataclass(frozen=True)
class SliceShape:
    """Resolved shape of one TPU slice."""

    accelerator: Accelerator
    topology: str
    chips: int
    num_hosts: int
    chips_per_host: int

    @property
    def bf16_peak_tflops(self) -> float:
        return self.chips * self.accelerator.bf16_peak_tflops


def parse_topology(topology: str, dims: int) -> tuple[int, ...]:
    parts = topology.lower().split("x")
    if len(parts) != dims:
        raise TopologyError(
            f"topology {topology!r} must have {dims} dimensions (e.g. "
            f"{'4x4' if dims == 2 else '2x2x2'})"
        )
    try:
        vals = tuple(int(p) for p in parts)
    except ValueError as e:
        raise TopologyError(f"topology {topology!r}: {e}") from None
    if any(v < 1 for v in vals):
        raise TopologyError(f"topology {topology!r}: dimensions must be >= 1")
    return vals


def accelerator_from_device_kind(device_kind: str) -> str:
    """Map a PJRT device_kind string ("TPU v5 lite", "TPU v5p", ...) to the
    user-facing generation key, defaulting to v5e for unknown kinds so MFU
    denominators stay conservative on this image's tunneled chip."""
    kind = device_kind.lower()
    if "v6" in kind:
        return "v6e"
    if "v5" in kind and ("lite" in kind or "v5e" in kind):
        return "v5e"
    if "v5" in kind:
        return "v5p"
    if "v4" in kind:
        return "v4"
    return "v5e"


def resolve(accelerator: str, topology: str) -> SliceShape:
    """Resolve {accelerator, topology} to chips/hosts/chips-per-host."""
    acc = ACCELERATORS.get(accelerator)
    if acc is None:
        raise TopologyError(
            f"unknown accelerator {accelerator!r}; supported: "
            f"{sorted(ACCELERATORS)}"
        )
    dims = parse_topology(topology, acc.dims)
    chips = math.prod(dims)
    if chips <= acc.max_single_host_chips:
        num_hosts, per_host = 1, chips
    else:
        if chips % acc.chips_per_host != 0:
            raise TopologyError(
                f"{accelerator} topology {topology}: {chips} chips not "
                f"divisible by {acc.chips_per_host} chips/host"
            )
        num_hosts, per_host = chips // acc.chips_per_host, acc.chips_per_host
    return SliceShape(
        accelerator=acc,
        topology=topology,
        chips=chips,
        num_hosts=num_hosts,
        chips_per_host=per_host,
    )
