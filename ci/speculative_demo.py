"""End-to-end speculative-decoding demo: train, then measure the speedup.

Speculative decoding's speed depends on draft/target agreement, which
random weights cannot produce — so this demo TRAINS both models (a
BENCH_CHIP-family target and a 2-layer draft) on the same learnable
synthetic stream (an affine token recurrence), then measures plain vs
speculative decode throughput on the chip.  Agreement comes from shared
learned structure, the honest mechanism, not from rigging the draft.

Prints one JSON line: plain tok/s, speculative tok/s, speedup, rounds.
Usage: python ci/speculative_demo.py [train_steps]
       python ci/speculative_demo.py --sample [train_steps]
--sample measures the temperature>0 rejection-sampling mode
(models/speculative.py speculative_sample) instead: plain sampled decode
vs speculative, with the measured acceptance rate per gamma.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.configs import BENCH_CHIP  # noqa: E402
from kubeflow_tpu.models.generate import decode_config, generate  # noqa: E402
from kubeflow_tpu.models.speculative import speculative_generate  # noqa: E402
from kubeflow_tpu.models.train import default_optimizer, setup_training  # noqa: E402
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: E402

VOCAB = 1024
SEQ = 512


def stream_batch(key, batch: int):
    """x_{t+1} = (a*x_t + c) mod V with per-row (a, c) from a small menu —
    learnable structure a 2-layer model picks up fast."""
    k1, k2, k3 = jax.random.split(key, 3)
    a = jax.random.choice(k1, jnp.array([3, 5, 7]), (batch, 1))
    c = jax.random.choice(k2, jnp.array([1, 11, 29]), (batch, 1))
    x0 = jax.random.randint(k3, (batch, 1), 0, VOCAB)

    def step(x, _):
        nxt = (a * x + c) % VOCAB
        return nxt, nxt

    _, xs = jax.lax.scan(step, x0, None, length=SEQ)
    seq = jnp.concatenate([x0, jnp.moveaxis(xs[..., 0], 0, 1)], axis=1)
    inputs = seq[:, :SEQ]
    return {"inputs": inputs, "targets": seq[:, 1:SEQ + 1]}


def train(cfg, steps: int, batch: int = 16, seed: int = 0):
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    setup = setup_training(
        cfg, mesh, batch_shape=(batch, SEQ),
        optimizer=default_optimizer(learning_rate=1e-3, warmup_steps=20,
                                    total_steps=max(steps, 21)))
    state = setup.state
    key = jax.random.PRNGKey(seed)
    for i in range(steps):
        key, sub = jax.random.split(key)
        state, metrics = setup.train_step(state, stream_batch(sub, batch))
    loss = float(np.asarray(metrics["loss"]))
    return state.params, loss


def train_pair(steps: int):
    """The shared target/draft pair both demo modes measure."""
    target_cfg = BENCH_CHIP.with_(vocab_size=VOCAB, max_seq_len=2048,
                                  loss_chunks=16)
    draft_cfg = target_cfg.with_(num_layers=2)
    t_params, t_loss = train(target_cfg, steps)
    d_params, d_loss = train(draft_cfg, steps, seed=1)
    print(f"trained: target loss {t_loss:.3f}, draft loss {d_loss:.3f}",
          file=sys.stderr)
    return target_cfg, t_params, t_loss, draft_cfg, d_params, d_loss


def best_of(fn, batch, prompt_len, n_new, n=3, with_key=False):
    """Best-of-n timing with a fresh prompt per window (the relay serves
    identical inputs from a result cache; see bench.py)."""
    best = 1e9
    for i in range(n):
        p = stream_batch(jax.random.PRNGKey(100 + i),
                         batch)["inputs"][:, :prompt_len]
        np.asarray(p)
        t0 = time.perf_counter()
        r = fn(p, jax.random.PRNGKey(i)) if with_key else fn(p)
        jax.tree.map(np.asarray, r)
        best = min(best, time.perf_counter() - t0)
    return batch * n_new / best


def main_sample(steps: int) -> None:
    """Temperature-sampling speculative decode on the trained pair:
    speedup AND acceptance rate vs gamma (the speed model is
    (accepted+1)/round; acceptance falls as gamma grows)."""
    from kubeflow_tpu.models.speculative import speculative_sample

    target_cfg, t_params, t_loss, draft_cfg, d_params, d_loss = \
        train_pair(steps)
    batch, prompt_len, n_new, temperature = 4, 64, 256, 0.8
    plain = jax.jit(lambda p, t, k: generate(
        target_cfg, p, t, max_new_tokens=n_new, temperature=temperature,
        rng=k))

    warm = stream_batch(jax.random.PRNGKey(42), batch)["inputs"][:, :prompt_len]
    np.asarray(plain(t_params, warm, jax.random.PRNGKey(0)))
    plain_tps = best_of(lambda p, k: plain(t_params, p, k),
                        batch, prompt_len, n_new, with_key=True)

    per_gamma = {}
    best_tps, best_gamma = 0.0, 0
    for gamma in (2, 4, 6):
        spec = jax.jit(lambda tp, dp, t, k, g=gamma: speculative_sample(
            target_cfg, tp, draft_cfg, dp, t, n_new, gamma=g,
            temperature=temperature, rng=k))
        _, rounds, rate = jax.tree.map(
            np.asarray, spec(t_params, d_params, warm, jax.random.PRNGKey(0)))
        tps = best_of(lambda p, k: spec(t_params, d_params, p, k),
                      batch, prompt_len, n_new, with_key=True)
        per_gamma[gamma] = {
            "tok_s": round(float(tps), 1),
            "accept_rate": round(float(rate), 3),
            "rounds_for_256": int(rounds),
        }
        if tps > best_tps:
            best_tps, best_gamma = tps, gamma
    print(json.dumps({
        "metric": "speculative_sampling_speedup_v5e",
        "value": round(best_tps / plain_tps, 3),
        "unit": "x",
        "vs_baseline": round(best_tps / plain_tps, 3),
        "detail": {
            "plain_sampled_tok_s": round(plain_tps, 1),
            "temperature": temperature,
            "best_gamma": best_gamma,
            "per_gamma": per_gamma,
            "train_steps": steps,
            "target_loss": round(t_loss, 3),
            "draft_loss": round(d_loss, 3),
        },
    }))


def main() -> None:
    if "--sample" in sys.argv:
        sys.argv.remove("--sample")
        main_sample(int(sys.argv[1]) if len(sys.argv) > 1 else 150)
        return
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    target_cfg, t_params, t_loss, draft_cfg, d_params, d_loss = \
        train_pair(steps)
    batch, prompt_len, n_new, gamma = 4, 64, 256, 4
    key = jax.random.PRNGKey(42)
    prompt = stream_batch(key, batch)["inputs"][:, :prompt_len]

    plain = jax.jit(lambda p, t: generate(
        target_cfg, p, t, max_new_tokens=n_new))
    spec = jax.jit(lambda tp, dp, t: speculative_generate(
        target_cfg, tp, draft_cfg, dp, t, n_new, gamma=gamma))

    np.asarray(plain(t_params, prompt))             # compile + warmup
    # exactness gate vs the SAME numerics speculative uses internally
    # (staged_kv=False): the staged throughput baseline reassociates the
    # softmax and can flip near-tie argmaxes (tests/test_generate.py
    # gates staged-vs-unstaged at >=0.95 agreement, not equality)
    ref = np.asarray(generate(
        decode_config(target_cfg).with_(staged_kv=False), t_params,
        prompt, max_new_tokens=n_new))
    out, rounds = spec(t_params, d_params, prompt)
    out = np.asarray(out)
    assert (out == ref).all(), "speculative output diverged from greedy"

    plain_tps = best_of(lambda p: plain(t_params, p),
                        batch, prompt_len, n_new)
    spec_tps = best_of(lambda p: spec(t_params, d_params, p),
                       batch, prompt_len, n_new)
    print(json.dumps({
        "metric": "speculative_speedup_v5e",
        "value": round(spec_tps / plain_tps, 3),
        "unit": "x",
        "vs_baseline": round(spec_tps / plain_tps, 3),
        "detail": {
            "plain_tok_s": round(plain_tps, 1),
            "speculative_tok_s": round(spec_tps, 1),
            "rounds_for_256": int(rounds),
            "ideal_rounds": -(-(n_new - 1) // gamma),
            "gamma": gamma,
            "train_steps": steps,
            "target_loss": round(t_loss, 3),
            "draft_loss": round(d_loss, 3),
        },
    }))


if __name__ == "__main__":
    main()
