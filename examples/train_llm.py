"""End-to-end in-notebook LLM workflow: data -> sharded training -> decode.

What a workbench user runs inside a TPU notebook this framework
provisioned — the whole compute-plane surface in one script:

  1. `tpu_init()` would consume the controller's env injection on a real
     slice (here: the local devices);
  2. `input_pipeline` streams host-sharded, device-prefetched LM batches;
  3. `setup_training` jits one SPMD step over a mesh using every populated
     parallelism axis;
  4. `generate` decodes from the trained weights with the KV cache.

Runs anywhere: on the 8-device virtual CPU mesh
(`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8`)
or a real slice.  Prints RESULT: OK when every stage behaves.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from kubeflow_tpu.models.configs import TINY  # noqa: E402
from kubeflow_tpu.models.generate import generate  # noqa: E402
from kubeflow_tpu.models.train import setup_training  # noqa: E402
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: E402
from kubeflow_tpu.runtime.data import input_pipeline  # noqa: E402
from kubeflow_tpu.runtime.telemetry import TelemetryAgent  # noqa: E402


def main() -> None:
    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].device_kind}")

    # a toy corpus with learnable structure: ascending token runs
    rng = np.random.default_rng(0)
    starts = rng.integers(0, TINY.vocab_size - 64, size=4000)
    tokens = np.concatenate([np.arange(s, s + 16) % TINY.vocab_size
                             for s in starts])

    n = len(devices)
    mesh = make_mesh(
        MeshConfig(data=-1,
                   fsdp=2 if n % 4 == 0 else 1,
                   tensor=2 if n % 2 == 0 else 1),
        devices=devices,
    )
    print(f"mesh: {dict(mesh.shape)}")
    setup = setup_training(TINY, mesh, batch_shape=(16, 64))

    pipe = input_pipeline(tokens, global_batch=16, seq_len=64, mesh=mesh,
                          num_epochs=None, prefetch=2)
    # the data-plane telemetry contract: one step_boundary() per synced
    # step; on a provisioned worker the summary publishes into the pod's
    # telemetry annotation for the control plane's straggler detection
    agent = TelemetryAgent(config=TINY, batch=16, seq_len=64,
                           num_chips=len(devices))
    state, first_loss, last_loss = setup.state, None, None
    agent.step_boundary()
    for step, batch in enumerate(pipe):
        state, metrics = setup.train_step(state, batch)
        loss = float(metrics["loss"])
        agent.step_boundary()
        first_loss = first_loss if first_loss is not None else loss
        last_loss = loss
        if step % 10 == 0:
            print(f"step {step:3d}  loss {loss:.4f}")
        if step >= 40:
            pipe.close()
            break
    assert last_loss < first_loss, (first_loss, last_loss)
    summary = agent.summary()
    print(f"trained: loss {first_loss:.4f} -> {last_loss:.4f}  "
          f"({summary['tokens_per_s']:.0f} tok/s, mfu {summary['mfu']:.4f},"
          f" {summary['bound']}-bound)")

    params = jax.device_get(state.params)
    prompt = np.stack([np.arange(10, 15), np.arange(100, 105)]).astype(np.int32)
    out = generate(TINY, params, jax.numpy.asarray(prompt), max_new_tokens=8)
    print("decoded:", np.asarray(out).tolist())
    assert out.shape == (2, 13)
    print("RESULT: OK")


if __name__ == "__main__":
    main()
