"""Runtime tests: worker-env bootstrap contract, checkpoint/cull hooks,
step metrics — the consumer side of the controller's env injection
(tpu/env.py must round-trip through runtime/init.py)."""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.runtime.checkpoint import (
    ACK_FILE,
    REQUEST_FILE,
    CheckpointManager,
    CullSignalWatcher,
    checkpoint_on_cull,
)
from kubeflow_tpu.runtime.init import parse_worker_env, tpu_init
from kubeflow_tpu.runtime.metrics import StepTimer, hbm_usage_bytes
from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.tpu import env as tpuenv
from kubeflow_tpu.tpu.topology import resolve


class TestWorkerEnvContract:
    def test_roundtrip_with_controller_injection(self):
        """The env the controller renders (tpu/env.py) must parse into the
        identity jax.distributed.initialize needs — index i of
        TPU_WORKER_HOSTNAMES == process_id i (SURVEY.md §7 hard parts)."""
        shape = resolve("v5e", "4x4")  # 16 chips, 4 hosts
        rendered = tpuenv.tpu_env_vars("nb", shape, slice_id=1, num_slices=2)
        env = {e["name"]: e.get("value", "") for e in rendered if "value" in e}
        env["TPU_WORKER_ID"] = "2"  # downward API would set this per pod
        identity = parse_worker_env(env)
        assert identity.hosts_per_slice == 4
        assert identity.num_slices == 2
        assert identity.slice_id == 1
        assert identity.process_id == 1 * 4 + 2
        assert identity.num_processes == 8
        assert identity.coordinator_address == (
            "nb-slice-0-0.nb-workers:8471"
        )
        # hostname list ordering == ordinal ordering
        assert identity.hostnames[2].startswith("nb-slice-1-2.")

    def test_single_host_is_noop(self):
        identity = tpu_init({"TPU_WORKER_HOSTNAMES": "only-one"})
        assert not identity.is_multihost


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        state = {"w": jnp.arange(8.0), "step": jnp.asarray(3)}
        mgr.save(3, state, wait=True)
        like = jax.tree.map(jnp.zeros_like, state)
        restored = mgr.restore(like)
        assert float(restored["w"][5]) == 5.0
        assert mgr.latest_step() == 3
        mgr.close()

    def test_restore_without_checkpoint_returns_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "empty"))
        assert mgr.restore({"w": jnp.zeros(2)}) is None
        mgr.close()

    def test_cull_signal_hook(self, tmp_path):
        signal_dir = tmp_path / "podinfo"
        signal_dir.mkdir()
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        watcher = CullSignalWatcher(str(signal_dir))
        hook = checkpoint_on_cull(mgr, watcher)
        state = {"w": jnp.ones(4)}
        assert hook(1, state) is False  # no signal yet
        (signal_dir / REQUEST_FILE).write_text("true")
        assert hook(2, state) is True
        assert (signal_dir / ACK_FILE).exists()
        assert mgr.latest_step() == 2
        assert hook(3, state) is False  # fires once
        mgr.close()


class TestTornCheckpoints:
    """Satellite: the local backend's kill-mid-save safety — save is
    temp-write -> fsync -> atomic rename, restore skips and GCs partial
    writes, so a worker killed mid-save can never resurrect a torn step."""

    def _mgr(self, tmp_path):
        return CheckpointManager(str(tmp_path / "local"), backend="local")

    def test_local_roundtrip(self, tmp_path):
        mgr = self._mgr(tmp_path)
        state = {"w": jnp.arange(8.0), "step": jnp.asarray(3)}
        mgr.save(3, state, wait=True)
        like = jax.tree.map(jnp.zeros_like, state)
        restored = mgr.restore(like)
        assert float(restored["w"][5]) == 5.0
        assert mgr.latest_step() == 3
        mgr.close()

    def test_kill_mid_save_leaves_previous_step_restorable(
            self, tmp_path, monkeypatch):
        mgr = self._mgr(tmp_path)
        state1 = {"w": jnp.ones(4)}
        mgr.save(1, state1)

        # the kill: the process dies after the temp write but BEFORE the
        # atomic rename — model it by making the rename never happen
        import os as _os

        def power_cut(src, dst):
            raise OSError("killed mid-save (before rename)")

        monkeypatch.setattr(_os, "replace", power_cut)
        with pytest.raises(OSError):
            mgr.save(2, {"w": jnp.full(4, 2.0)})
        monkeypatch.undo()
        # the torn write is visible only as a temp file, never a step
        assert list(mgr.directory.glob(".tmp-*"))
        assert mgr.latest_step() == 1

        # a fresh manager (the restarted worker) restores step 1 and GCs
        # the partial
        mgr2 = CheckpointManager(str(mgr.directory), backend="local")
        restored = mgr2.restore({"w": jnp.zeros(4)})
        assert float(restored["w"][0]) == 1.0
        assert not list(mgr2.directory.glob(".tmp-*"))

    def test_corrupt_step_skipped_and_gced_on_restore(self, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.save(1, {"w": jnp.ones(2)})
        mgr.save(2, {"w": jnp.full(2, 2.0)})
        # a torn final file (disk-level corruption) must not poison boot:
        # restore falls back to the next-older step and GCs the husk
        (mgr.directory / "step_3.ckpt").write_bytes(b"\x00garbage")
        assert mgr.latest_step() == 3
        restored = mgr.restore({"w": jnp.zeros(2)})
        assert float(restored["w"][0]) == 2.0
        assert not (mgr.directory / "step_3.ckpt").exists()
        assert mgr.latest_step() == 2

    def test_max_to_keep_prunes_oldest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "p"), max_to_keep=2,
                                backend="local")
        for step in (1, 2, 3):
            mgr.save(step, {"w": jnp.full(2, float(step))})
        assert mgr._local_steps() == [2, 3]


class TestCheckpointSidecar:
    """The pod side of the session-state contract (core/sessionstate.py):
    periodic snapshots by interval, forced snapshot + ack on the cull
    signal, restore from the stamped CHECKPOINT_RESTORE_* env."""

    def _store(self, clock):
        from kubeflow_tpu.core.sessionstate import InMemorySessionStore

        return InMemorySessionStore(clock=clock)

    def test_periodic_interval(self):
        from kubeflow_tpu.runtime.checkpoint import CheckpointSidecar
        from kubeflow_tpu.utils.clock import FakeClock

        clock = FakeClock(start=0.0)
        store = self._store(clock)
        sidecar = CheckpointSidecar(store, "u1", "nb", 0, interval_s=60.0,
                                    time_fn=clock.now)
        assert sidecar.maybe_snapshot(lambda: b"s0") is not None  # first
        assert sidecar.maybe_snapshot(lambda: b"s1") is None      # too soon
        clock.advance(61)
        info = sidecar.maybe_snapshot(lambda: b"s1")
        assert info.generation == 2 and info.trigger == "periodic"

    def test_cull_signal_forces_snapshot_and_acks(self, tmp_path):
        from kubeflow_tpu.runtime.checkpoint import CheckpointSidecar
        from kubeflow_tpu.utils.clock import FakeClock

        clock = FakeClock(start=0.0)
        store = self._store(clock)
        signal_dir = tmp_path / "podinfo"
        signal_dir.mkdir()
        watcher = CullSignalWatcher(str(signal_dir))
        sidecar = CheckpointSidecar(store, "u1", "nb", 0, interval_s=1e9,
                                    watcher=watcher, time_fn=clock.now)
        sidecar.maybe_snapshot(lambda: b"base")
        (signal_dir / REQUEST_FILE).write_text("true")
        info = sidecar.maybe_snapshot(lambda: b"final-state")
        assert info is not None and info.trigger == "cull"
        assert (signal_dir / ACK_FILE).exists()
        # fires once per cull cycle
        assert sidecar.maybe_snapshot(lambda: b"again") is None

    def test_restore_instructions_and_payload(self):
        from kubeflow_tpu.runtime.checkpoint import (
            CheckpointSidecar,
            restore_instructions,
        )
        from kubeflow_tpu.utils.clock import FakeClock

        assert restore_instructions({}) is None
        assert restore_instructions(
            {"CHECKPOINT_RESTORE_URI": "mem://x",
             "CHECKPOINT_RESTORE_GENERATION": "nope"}) is None
        clock = FakeClock()
        store = self._store(clock)
        info = store.put("u1", "nb", 0, b"the-session")
        sidecar = CheckpointSidecar(store, "u1", "nb", 0,
                                    time_fn=clock.now)
        env = {"CHECKPOINT_RESTORE_URI": store.uri,
               "CHECKPOINT_RESTORE_GENERATION": str(info.generation)}
        assert sidecar.restore_payload(env) == b"the-session"
        assert sidecar.restore_payload({}) is None  # cold start

    def test_from_env_honors_contract(self, tmp_path):
        from kubeflow_tpu.runtime.checkpoint import CheckpointSidecar

        assert CheckpointSidecar.from_env("u1", "nb", 0, env={}) is None
        sidecar = CheckpointSidecar.from_env(
            "u1", "nb", 1,
            env={"CHECKPOINT_STORE_URI": f"file://{tmp_path}/s",
                 "CHECKPOINT_INTERVAL_S": "45"})
        assert sidecar is not None and sidecar.interval_s == 45.0
        info = sidecar.snapshot_now(b"pre-stop-state")
        assert info.trigger == "pre-stop"
        assert sidecar.store.payload("u1", "nb", 1) == b"pre-stop-state"


class TestStepMetrics:
    def test_mfu_math(self):
        timer = StepTimer(TINY, batch=4, seq_len=128, num_chips=1)
        timer._times = [0.1, 0.1]
        assert timer.tokens_per_s == pytest.approx(4 * 128 / 0.1)
        assert 0.0 < timer.mfu < 1e-3  # tiny model, far from peak
        text = timer.prometheus_text()
        assert "notebook_training_mfu_ratio" in text
        assert "notebook_training_tokens_per_second" in text
        # Registry-rendered exposition: full HELP/TYPE metadata
        assert "# TYPE notebook_training_step_duration_seconds histogram" \
            in text
        assert "# TYPE notebook_training_mfu_ratio gauge" in text

    def test_injectable_clock_feeds_step_histogram(self):
        """The satellite: timing reads the injected monotonic clock, not
        time.perf_counter, so step telemetry is exact under a FakeClock."""
        from kubeflow_tpu.utils.clock import FakeClock

        clock = FakeClock(start=0.0)
        timer = StepTimer(TINY, batch=4, seq_len=128, num_chips=1,
                          time_fn=clock.now)
        timer.observe()            # arms the timer; no interval yet
        clock.advance(0.1)
        timer.observe()
        clock.advance(0.3)
        timer.observe()
        assert timer.step_time_s == pytest.approx(0.2)
        hist = timer.registry.get("notebook_training_step_duration_seconds")
        assert hist.count_value() == 2
        assert hist.sum_value() == pytest.approx(0.4)
        buckets = hist.bucket_counts()
        assert buckets[0.1] == 1   # the 0.1s step
        assert buckets[0.5] == 2   # both by 0.5s
        assert timer.tokens_per_s == pytest.approx(4 * 128 / 0.2)

    def test_families_shared_registry_and_naming_rule(self):
        """Families register on a shared Registry (drift-check inventory)
        and every name passes the ci/lint.py metric-naming conventions."""
        from kubeflow_tpu.runtime.metrics import register_step_metrics
        from kubeflow_tpu.utils.metrics import Registry

        reg = Registry()
        register_step_metrics(reg)
        fams = dict(reg.families())
        assert fams["notebook_training_step_duration_seconds"] == "histogram"
        assert fams["notebook_training_tokens_per_second"] == "gauge"
        assert fams["notebook_training_mfu_ratio"] == "gauge"
        assert fams["notebook_training_hbm_bytes_in_use"] == "gauge"
        # idempotent re-registration (two timers sharing one registry)
        register_step_metrics(reg)
        assert len(reg.families()) == 4
        for name, kind in fams.items():
            if name.endswith("_total"):
                assert kind == "counter", name
            if name.endswith("_seconds"):
                assert kind in ("histogram", "gauge"), name

    def test_hbm_usage_shape(self):
        usage = hbm_usage_bytes()
        assert len(usage) == jax.local_device_count()
