"""Parallelism: device-mesh construction (ICI/DCN-aware) and logical-axis
sharding rules binding models to the mesh."""

from .mesh import MESH_AXES, MeshConfig, make_mesh, mesh_for_slice
from .sharding import DEFAULT_RULES, constrain, logical_sharding, logical_to_spec

__all__ = [
    "DEFAULT_RULES", "MESH_AXES", "MeshConfig", "constrain",
    "logical_sharding", "logical_to_spec", "make_mesh", "mesh_for_slice",
]
