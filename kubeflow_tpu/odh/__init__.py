"""ODH extension plane: routing, auth, webhooks, and data-science
integrations layered over the core notebook controller (reference:
components/odh-notebook-controller)."""

from .controller import OpenshiftNotebookReconciler, setup_odh_controllers
from .webhook import NotebookMutatingWebhook, NotebookValidatingWebhook

__all__ = [
    "NotebookMutatingWebhook",
    "NotebookValidatingWebhook",
    "OpenshiftNotebookReconciler",
    "setup_odh_controllers",
]
