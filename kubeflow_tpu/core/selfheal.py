"""Slice-atomic self-healing: disruption detection + budgeted recovery.

The status computation has always *named* the failure mode — "partial
readiness is a degraded slice: collectives hang"
(notebook_controller._compute_and_write_status) — without acting on it: a
crashed worker, a preempted TPU node, or a stuck-Pending pod left a
multi-host notebook wedged until a human intervened.  This module closes
the loop, in the shape NotebookOS (arXiv:2503.20591) and ElasticNotebook
(arXiv:2309.11083) argue interactive platforms need:

- `classify_worker` turns the pod state the reconciler already lists into
  a disruption verdict: pod `Failed`, CrashLoopBackOff (container
  `waiting.reason`), node-driven deletion/preemption (dangling or unready
  `spec.nodeName`), or Pending beyond a configurable schedule deadline.
  Healthy and transient states (Running-not-yet-Ready, a pod
  mid-recreate, Pending within the deadline) must never trigger recovery.

- `RecoveryEngine` restarts the *entire affected slice* — JAX collectives
  cannot survive partial membership, so single-pod surgery is never
  correct — under a restart budget: exponential backoff between attempts
  (`RECOVERY_BACKOFF_*` knobs on CoreConfig), a capped attempt count
  within a sliding window, and a terminal `RecoveryExhausted=True`
  condition (+ Warning event) once the budget is spent, so the controller
  stops churning a permanently broken slice.

- With a session-state store wired (core/sessionstate.py, CHECKPOINT_*
  knobs), the engine prefers a `migrate` verb over the bare restart:
  request/confirm a final snapshot while the slice is still reachable —
  else fall back to the freshest stored checkpoint within
  CHECKPOINT_MAX_AGE_S — then write the restore intent into
  `status.sessionState` (write-ahead), re-stamp the slice StatefulSet so
  the recreated pods carry CHECKPOINT_RESTORE_URI/_GENERATION, and only
  then delete the pods.  A stale/absent checkpoint degrades to the bare
  restart; migrate and restart share ONE attempt budget.  The same verb
  serves *voluntary* migration — a drain/defrag annotation
  (constants.ANNOTATION_MIGRATE) or a worker parked on a cordoned
  (unschedulable) Node — with the guard that a healthy session is never
  torn down without a secured checkpoint.  Verb precedence:
  cull > migrate > restart.

- For a **replicated** notebook (spec.replication: one primary gang plus
  follower gangs continuously replaying the checkpoint-delta stream,
  core/sessionstate.py) the engine prefers a `promote` verb over both:
  primary-gang failure elects the freshest caught-up follower (follower
  pods positively stamp their replayed position,
  ANNOTATION_REPLICA_GENERATION/SEQ) and flips the primary pointer in
  `status.replication` under epoch fencing — the same pattern as the
  sharded control plane's map (kube/shard.py): the epoch is bumped in
  the SAME commit that writes the write-ahead promotion record
  (phase="promoting"), the session store's write fence is raised to the
  new epoch, and only then is the new primary named
  (phase="promoted").  The fence raise is the linearization point: a
  demoted (zombie) primary's write either landed before it — and the
  promoted follower replays it during catch-up — or raises
  StaleWriterError and was never acked.  A crash anywhere in between
  resumes from the promotion record (re-fence is idempotent, the fence
  is a monotonic max).  The demoted gang then heals as a follower
  through the ordinary restart budget; migrate is never used for
  replicated notebooks (the delta stream IS the migration).

All bookkeeping (per-slice attempt timestamps, last-restart time, backoff
deadline, disruption stamp, exhaustion flag — and the migrate verb's
restore intent) is persisted in `status.sliceRecovery` /
`status.sessionState` on the CR — controller memory holds nothing — so a
manager crash or leader failover resumes the budget AND any in-flight
migration instead of resetting them.  The bookkeeping write happens
BEFORE the pod deletes (write-ahead): a crash mid-restart can lose the
restart, never the attempt charge, and never the restore instructions.
"""

from __future__ import annotations

import copy
import logging
from typing import Callable, Optional

from ..api.types import CONDITION_RECOVERY_EXHAUSTED, Notebook
from ..kube import (
    ApiServer,
    EventRecorder,
    KubeObject,
    NotFoundError,
    retry_on_conflict,
)
from ..utils import tracing
from ..utils.clock import Clock, parse_iso
from ..utils.config import CoreConfig
from . import constants as C
from .metrics import NotebookMetrics
from .sessionstate import (
    SessionStateStore,
    SnapshotInfo,
    TRIGGER_FINAL,
)

logger = logging.getLogger("kubeflow_tpu.selfheal")

# recovery attempts open a `recover` phase span on the shared context
# stack, parenting onto the manager's per-attempt reconcile root — the
# flight recorder then shows recovery time per attempt (/debug/reconciles)
_TRACER = tracing.get_tracer("kubeflow_tpu.core.selfheal")

# Disruption classifications — a bounded set, because they label
# notebook_slice_restarts_total{reason}.
REASON_POD_FAILED = "pod-failed"
REASON_CRASH_LOOP = "crash-loop"
REASON_NODE_GONE = "node-gone"
REASON_PENDING_TIMEOUT = "pending-timeout"
# a slice restart performed by the migrate verb (checkpoint secured) —
# distinguishes state-preserving restarts from bare ones in the counter
REASON_MIGRATE = "migrate"
# transient marker, not yet a disruption: a Pending worker becomes
# REASON_PENDING_TIMEOUT only once the schedule deadline passes
PENDING = "pending"

# migrate triggers/results — bounded sets, they label
# notebook_migrations_total{trigger,result}
MIGRATE_TRIGGER_FAILURE = "failure"
MIGRATE_TRIGGER_DRAIN = "drain"
MIGRATE_TRIGGER_DEFRAG = "defrag"
MIGRATE_TRIGGER_NODE_DRAIN = "node-drain"
MIGRATE_RESULT_MIGRATED = "migrated"          # verb issued with a checkpoint
MIGRATE_RESULT_RESTORED = "restored"          # slice Healthy post-restore
MIGRATE_RESULT_FALLBACK = "fallback-restart"  # stale/absent ckpt -> bare
MIGRATE_RESULT_SKIPPED = "skipped"            # voluntary without a ckpt

# promote verb (replicated notebooks): the internal verb tag plus the
# bounded result set labelling notebook_promotions_total{result}
VERB_PROMOTE = "promote"
PROMOTE_RESULT_PROMOTED = "promoted"        # follower elected + flipped
PROMOTE_RESULT_LOST_RACE = "lost-race"      # another promoter committed first
PROMOTE_RESULT_NO_CANDIDATE = "no-candidate"  # no caught-up follower

# event reasons (kubectl describe notebook)
EVENT_SLICE_RECOVERY = "SliceRecovery"
EVENT_RECOVERY_EXHAUSTED = "RecoveryExhausted"
EVENT_RECOVERY_RESTORED = "RecoveryRestored"
EVENT_SLICE_MIGRATION = "SliceMigration"
EVENT_MIGRATION_COMPLETE = "MigrationComplete"
EVENT_MIGRATION_SKIPPED = "MigrationSkipped"
EVENT_PRIMARY_PROMOTED = "PrimaryPromoted"


class SliceRestartError(Exception):
    """Aggregate of per-pod delete failures from a slice-atomic restart.

    Raised only after EVERY pod of the slice has been attempted — a
    transient error on one worker must not leave the rest of the slice
    untried, which is exactly the partial-restart state slice-atomicity
    forbids.  The reconcile fails with this and the manager's backoff
    retries the whole slice; a half-restarted slice is therefore never
    reported as recovered."""

    def __init__(self, errors: list[Exception], attempted: int) -> None:
        self.errors = errors
        self.attempted = attempted
        super().__init__(
            f"slice restart: {len(errors)}/{attempted} pod deletes failed; "
            f"first: {errors[0]}")


def _pod_ready(pod: KubeObject) -> bool:
    return any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in pod.body.get("status", {}).get("conditions", [])
    )


def classify_worker(pod: KubeObject, api: ApiServer,
                    node_cache: Optional[dict] = None) -> Optional[str]:
    """Classify one worker pod from the state the reconciler already sees.

    Returns a REASON_* constant for a disrupted worker, PENDING for a pod
    that is merely waiting to schedule/start (only the deadline makes that
    a disruption), or None for healthy and transient states that must NOT
    trigger recovery.  `node_cache` memoizes Node lookups across one
    engine pass (a slice's workers usually share few nodes)."""
    status = pod.body.get("status", {}) or {}
    if status.get("phase") == "Failed":
        return REASON_POD_FAILED
    for cs in status.get("containerStatuses", []) or []:
        waiting = (cs.get("state") or {}).get("waiting") or {}
        if waiting.get("reason") == "CrashLoopBackOff":
            return REASON_CRASH_LOOP
    node = _node_of(pod, api, node_cache)
    if pod.spec.get("nodeName", ""):
        if node is None:
            # the node object vanished under the pod: preemption or
            # scale-down, before the node controller reaped the pod
            return REASON_NODE_GONE
        node_ready = any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in node.body.get("status", {}).get("conditions", [])
        )
        if not node_ready:
            return REASON_NODE_GONE
    if status.get("phase") == "Pending":
        return PENDING
    return None


def _node_of(pod: KubeObject, api: ApiServer,
             node_cache: Optional[dict]) -> Optional[KubeObject]:
    node_name = pod.spec.get("nodeName", "")
    if not node_name:
        return None
    if node_cache is not None and node_name in node_cache:
        return node_cache[node_name]
    node = api.try_get("Node", "", node_name)
    if node_cache is not None:
        node_cache[node_name] = node
    return node


def node_drained(pod: KubeObject, api: ApiServer,
                 node_cache: Optional[dict] = None) -> bool:
    """A worker parked on a cordoned Node (`spec.unschedulable`) is a
    voluntary-migration candidate: the node is being drained, not failed —
    classify_worker correctly stays quiet, the migrate verb moves it."""
    node = _node_of(pod, api, node_cache)
    return bool(node is not None and node.spec.get("unschedulable"))


def _replica_freshness(pods: list[KubeObject]) -> Optional[tuple[int, int,
                                                                 str]]:
    """Catch-up freshness of one replica gang from the positive
    ANNOTATION_REPLICA_* stamps its runtime writes as it replays the
    checkpoint-delta stream.  The gang's freshness is its SLOWEST worker's
    (generation, seq) — a gang is only as caught up as its laggard — and a
    single unstamped worker makes the whole gang unknown (None): election
    needs positive evidence, absence never reads as caught up."""
    worst: Optional[tuple[int, int, str]] = None
    for pod in pods:
        ann = pod.metadata.annotations
        gen_raw = ann.get(C.ANNOTATION_REPLICA_GENERATION)
        seq_raw = ann.get(C.ANNOTATION_REPLICA_SEQ)
        if gen_raw is None or seq_raw is None:
            return None
        try:
            cur = (int(gen_raw), int(seq_raw),
                   ann.get(C.ANNOTATION_REPLICA_DIGEST, ""))
        except ValueError:
            return None
        if worst is None or cur[:2] < worst[:2]:
            worst = cur
    return worst


class RecoveryEngine:
    """Budgeted slice-atomic recovery, driven from the notebook reconcile.

    `maybe_recover` runs after the status pass: it classifies every worker
    of every slice, and for a disrupted slice either waits out the current
    backoff (returning a requeue-after hint), migrates or restarts the
    whole slice (write-ahead bookkeeping, then delete every pod), or —
    once the sliding-window attempt budget is spent — escalates to the
    terminal RecoveryExhausted condition and stops touching the slice
    until an operator heals it (at which point the budget resets)."""

    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        metrics: NotebookMetrics,
        recorder: EventRecorder,
        clock: Optional[Clock] = None,
        cache=None,
        session: Optional[SessionStateStore] = None,
    ) -> None:
        self.api = api
        self.cfg = cfg
        self.metrics = metrics
        self.recorder = recorder
        self.clock = clock or Clock()
        # informer cache for detection-path reads (Notebook freshness,
        # Node health in classify_worker); writes always go live
        self.cache = cache
        # session-state store (core/sessionstate.py): when wired, the
        # migrate verb is preferred over bare restart
        self.session = session

    # -- entry point ----------------------------------------------------------
    def maybe_recover(
        self,
        nb: Notebook,
        live_names: list[str],
        pods_of: Callable[[str], list[KubeObject]],
        restart_slice: Callable[[str], None],
        stamp_restore: Optional[Callable[[str, int], None]] = None,
    ) -> float:
        """One recovery pass; returns the requeue-after hint in seconds
        (0.0 = nothing scheduled).  `live_names` is ordered slice 0 first,
        as the reconciler builds it — for a replicated notebook it covers
        EVERY replica gang in replica-major order (replica 0's slices,
        then replica 1's, ...); `restart_slice` must delete every pod
        of the named slice's StatefulSet, aggregating errors
        (NotebookReconciler._restart_pods); `stamp_restore(live_name, idx)`
        must sync the live StatefulSet template with the freshly written
        restore intent so the recreated pods boot with the
        CHECKPOINT_RESTORE_* env (NotebookReconciler._stamp_restore)."""
        tpu = nb.tpu
        if tpu is None or not self.cfg.enable_self_healing:
            return 0.0
        rep_spec = nb.replication
        num_slices = tpu.slices
        reader = self.cache if self.cache is not None else self.api
        live = reader.try_get("Notebook", nb.namespace, nb.name)
        if live is None or live.metadata.deletion_timestamp is not None:
            return 0.0
        status = live.body.get("status", {}) or {}
        recovery = copy.deepcopy(status.get("sliceRecovery") or {})
        prev_recovery = copy.deepcopy(recovery)
        session_state = copy.deepcopy(status.get("sessionState") or {})
        prev_session = copy.deepcopy(session_state)

        # Culling precedence (cull > migrate > restart): a stop-annotated
        # notebook (slice health Stopping/Stopped) is being parked on
        # purpose — "recovering" it would fight the cull pod-for-pod.
        # Once fully Stopped, stale recovery bookkeeping (including an
        # exhaustion verdict) is dropped so an un-culled notebook starts
        # with a fresh budget; status.sessionState deliberately SURVIVES
        # the stop — the pre-cull checkpoint is what an un-culled notebook
        # restores from.
        if C.STOP_ANNOTATION in live.metadata.annotations or \
                status.get("sliceHealth") in ("Stopping", "Stopped"):
            if recovery and status.get("sliceHealth") == "Stopped":
                self._write_bookkeeping(nb, {})
            return 0.0

        # Preemption precedence (cull > preempt > migrate > restart): a
        # queued gang — including a preemption victim mid-teardown or
        # already re-queued — holds no placed capacity; "recovering" it
        # would fight the preemption engine pod-for-pod exactly the way
        # the cull guard above prevents for culls.  Its sessionState
        # (the checkpoint-then-preempt restore intent) survives untouched
        # and rides the ordinary restore path once the gang re-places.
        if C.ANNOTATION_QUEUED in live.metadata.annotations:
            return 0.0

        # voluntary migration request: drain/defrag annotation on the CR
        ann_raw = live.metadata.annotations.get(
            C.ANNOTATION_MIGRATE, "").strip().lower()
        ann_trigger = None
        if ann_raw:
            ann_trigger = ann_raw if ann_raw in (
                MIGRATE_TRIGGER_DRAIN, MIGRATE_TRIGGER_DEFRAG,
            ) else MIGRATE_TRIGGER_DRAIN

        # -- pass 1: pure detection (no span unless there is work) ------------
        shape = tpu.shape
        node_cache: dict[str, Optional[KubeObject]] = {}
        detections: list[tuple] = []
        gang_fresh: dict[int, Optional[tuple[int, int, str]]] = {}
        for idx, live_name in enumerate(live_names):
            pods = sorted(pods_of(live_name), key=lambda p: p.name)
            if rep_spec is not None:
                gang_fresh[idx] = _replica_freshness(pods)
            reasons: list[tuple[str, str]] = []
            pending = False
            ready = 0
            drained = False
            for pod in pods:
                verdict = classify_worker(pod, reader, node_cache)
                if verdict == PENDING:
                    pending = True
                elif verdict is not None:
                    reasons.append((pod.name, verdict))
                elif node_drained(pod, reader, node_cache):
                    drained = True
                if _pod_ready(pod):
                    ready += 1
            healthy = not reasons and not pending and ready >= shape.num_hosts
            # a disruption wins over a voluntary request (the failure path
            # migrates too, just under the "failure" trigger)
            trigger = None
            if not reasons and not pending:
                trigger = ann_trigger or (
                    MIGRATE_TRIGGER_NODE_DRAIN if drained else None)
            # migration-completeness audit: a worker positively stamped
            # with a DIFFERENT restored generation than the in-flight
            # intent survived the restart (e.g. a delete that failed
            # mid-sweep) and still runs the old session — the migration
            # must not finalize over it.  Absent stamps stay neutral
            # (runtimes without the stamping agent must not wedge here).
            stale_session = False
            target = (session_state.get(str(idx)) or {})
            if target.get("phase") == "migrating" and \
                    target.get("restoreGeneration") is not None:
                want = str(target["restoreGeneration"])
                for pod in pods:
                    got = pod.metadata.annotations.get(
                        C.ANNOTATION_RESTORED_GENERATION)
                    if got is not None and got != want:
                        stale_session = True
                        break
            detections.append((idx, live_name, reasons, pending, healthy,
                               trigger, stale_session))

        # -- replicated tier: followers record + promotion decision -----------
        replication = None
        prev_replication = None
        promote_entry = None
        no_candidate = False
        skip_gangs: set[int] = set()
        primary_replica = 0
        if rep_spec is not None:
            replication = copy.deepcopy(status.get("replication") or {})
            prev_replication = copy.deepcopy(replication)
            replication.setdefault("epoch", 1)
            replication.setdefault("primary", 0)
            primary_replica = replication["primary"]
            self._record_followers(rep_spec, num_slices, detections,
                                   gang_fresh, replication)
            promote_entry, skip_gangs, no_candidate = \
                self._promotion_decision(nb, rep_spec, num_slices,
                                         detections, gang_fresh,
                                         replication, recovery)

        migrating_inflight = any(
            s.get("phase") == "migrating" for s in session_state.values())
        if not recovery and not migrating_inflight and not any(
                reasons or pending or trigger
                for _, _, reasons, pending, _, trigger, _ in detections):
            if promote_entry is None and \
                    (replication is None or replication == prev_replication):
                return 0.0

        # -- pass 2: decisions, under the `recover` phase span ----------------
        now = self.clock.now()
        requeue = 0.0
        restarts: list[dict] = []
        events: list[tuple[str, str, str]] = []
        notes = {"deferred": False}
        with _TRACER.start_span(
            "recover", {"phase": "recover", "namespace": nb.namespace,
                        "notebook": nb.name}
        ) as span:
            if promote_entry is not None:
                # first in the verb queue: the promotion record must land
                # (and the fence rise) before any gang of this pass dies
                restarts.append(promote_entry)
                requeue = _merge_requeue(
                    requeue, self.cfg.recovery_backoff_base_s)
            if no_candidate:
                # primary disrupted but no caught-up follower to elect —
                # fall through to the ordinary restart verbs below
                self.metrics.promotions.labels(
                    nb.namespace, PROMOTE_RESULT_NO_CANDIDATE).inc()
                span.add_event("promote.no_candidate", {
                    "primary": primary_replica})
            for idx, live_name, reasons, pending, healthy, trigger, \
                    stale_session in detections:
                if idx in skip_gangs:
                    # the gang being demoted this pass: promotion replaces
                    # its restart; it heals as a follower from next pass
                    continue
                requeue = _merge_requeue(requeue, self._slice_pass(
                    nb, idx, live_name, reasons, pending, healthy, trigger,
                    stale_session, recovery, session_state, restarts,
                    events, notes, span, now,
                    allow_migrate=rep_spec is None,
                    observe_recovery=rep_spec is None or
                    idx // num_slices == primary_replica))

            # per-slice passes mutate their state dicts in place; drop
            # entries that emptied out so the persisted bookkeeping stays
            # minimal (and the no-op status check stays meaningful)
            for key in [k for k, s in recovery.items() if not s]:
                recovery.pop(key)
            for key in [k for k, s in session_state.items() if not s]:
                session_state.pop(key)
            exhausted = sorted(
                k for k, s in recovery.items() if s.get("exhausted"))
            # write-ahead: the budget charge AND the restore intent must
            # survive a crash between here and the pod deletes below — a
            # manager failover resumes the migration from
            # status.sessionState instead of forgetting it.  The call is
            # unconditional (the unchanged-bookkeeping no-op check lives
            # inside) so it dominates every restart on the CFG — enforced
            # by ci/analyzers/write_ahead.py.
            self._write_bookkeeping(nb, recovery, exhausted, session_state,
                                    replication=replication,
                                    skip_if_unchanged=(prev_recovery,
                                                       prev_session,
                                                       prev_replication))
            for etype, reason, message in events:
                self.recorder.event(nb.obj, etype, reason, message)

            for entry in restarts:
                if entry["verb"] == VERB_PROMOTE:
                    self._execute_promote(nb, entry)
                elif entry["verb"] == REASON_MIGRATE:
                    self._execute_migrate(nb, entry, stamp_restore,
                                          restart_slice)
                else:
                    self._execute_restart(nb, entry, span, stamp_restore,
                                          restart_slice)

            # the drain/defrag annotation is consumed once every slice got
            # its decision this pass; a deferred slice (backoff still
            # armed, pods mid-recreate) keeps it for the requeued retry
            if ann_trigger and not notes["deferred"]:
                self._clear_migrate_annotation(nb)
        return requeue

    # -- replicated tier ------------------------------------------------------
    def _record_followers(self, rep_spec, num_slices, detections,
                          gang_fresh, replication) -> None:
        """Mirror follower readiness + catch-up freshness into
        status.replication.followers — the chaos soak's assertable record
        and the operator's view of how hot each standby is."""
        p = replication["primary"]
        followers: dict = {}
        for r in range(rep_spec.replicas):
            if r == p:
                continue
            rec: dict = {"ready": True, "slices": {}}
            for s in range(num_slices):
                g = r * num_slices + s
                if g >= len(detections) or not detections[g][4]:
                    rec["ready"] = False
                if g < len(detections):
                    fresh = gang_fresh.get(g)
                    if fresh is not None:
                        rec["slices"][str(s)] = {
                            "generation": fresh[0], "seq": fresh[1],
                            "digest": fresh[2]}
            followers[str(r)] = rec
        replication["followers"] = followers

    def _promotion_decision(self, nb, rep_spec, num_slices, detections,
                            gang_fresh, replication,
                            recovery) -> tuple[Optional[dict], set[int],
                                               bool]:
        """Decide the promote verb for this pass.  Returns
        (promote_entry | None, gang indexes whose restart the promotion
        replaces this pass, no-candidate flag).  An in-flight promotion
        record (phase=="promoting" — a crash between the record commit
        and the flip) resumes ahead of any fresh election."""
        p = replication["primary"]
        promo = replication.get("promotion") or {}
        if promo.get("phase") == "promoting":
            started = promo.get("startedAt")
            entry = {
                "verb": VERB_PROMOTE, "resume": True,
                "epoch": promo["epoch"], "from": promo["from"],
                "to": promo["to"], "reason": promo.get("reason", ""),
                "disrupted_at": parse_iso(started) if started else None,
            }
            skip = set(range(promo["from"] * num_slices,
                             (promo["from"] + 1) * num_slices))
            return entry, skip, False
        primary_gangs = range(p * num_slices, (p + 1) * num_slices)
        primary_reasons = [
            det[2] for det in detections
            if det[0] in primary_gangs and det[2]]
        if not primary_reasons:
            return None, set(), False
        if self.session is None:
            # no delta stream to verify catch-up against: promotion would
            # be a blind guess, so the ordinary verbs take over
            return None, set(), True
        best: Optional[tuple[tuple, int]] = None
        for r in range(rep_spec.replicas):
            if r == p:
                continue
            score = self._candidate_score(nb, r, num_slices, detections,
                                          gang_fresh)
            if score is None:
                continue
            if best is None or score > best[0]:
                best = (score, r)
        if best is None:
            return None, set(), True
        # duration anchor: the earliest persisted disruption stamp among
        # the primary's gangs (a backoff/fault-delayed pass keeps charging
        # the same incident), else this very detection
        disrupted_at = None
        for g in primary_gangs:
            st = recovery.get(str(g)) or {}
            if st.get("disruptedAt"):
                t = parse_iso(st["disruptedAt"])
                disrupted_at = t if disrupted_at is None \
                    else min(disrupted_at, t)
        entry = {
            "verb": VERB_PROMOTE, "resume": False,
            "epoch": replication["epoch"] + 1,
            "from": p, "to": best[1],
            "reason": primary_reasons[0][0][1],
            "disrupted_at": disrupted_at if disrupted_at is not None
            else self.clock.now(),
        }
        return entry, set(primary_gangs), False

    def _candidate_score(self, nb, r, num_slices, detections,
                         gang_fresh) -> Optional[tuple]:
        """Election score of follower replica r: the per-slice
        (generation, seq) freshness tuple, or None when any gang is
        unhealthy, unstamped, or trailing the chain head by more than
        REPLICATION_MAX_LAG (promotion needs positive evidence the state
        is there — a missing stamp never reads as caught up)."""
        score = []
        for s in range(num_slices):
            g = r * num_slices + s
            if g >= len(detections) or not detections[g][4]:
                return None
            fresh = gang_fresh.get(g)
            if fresh is None:
                return None
            head = self.session.chain_head(nb.namespace, nb.name, s)
            if head is None:
                return None
            gen, seq, _digest = fresh
            head_gen, head_seq, _head_digest = head
            lag = (1 + head_seq) if gen != head_gen \
                else max(head_seq - seq, 0)
            if lag > self.cfg.replication_max_lag:
                return None
            score.append((gen, seq))
        return tuple(score)

    def _execute_promote(self, nb, entry) -> None:
        """The promote verb, under its own `replication.promote` phase
        span.  Protocol order is the guarantee:

        1. commit the write-ahead promotion record, bumping the epoch in
           the SAME status write (CAS on the old epoch — a racing
           promoter loses cleanly);
        2. raise the session store's write fence to the new epoch — the
           linearization point after which the demoted primary cannot ack
           a write;
        3. commit the flip: name the new primary, phase="promoted".

        A crash between any two steps resumes via the promotion record
        (entry["resume"]): step 2 is a monotonic max and step 3 checks
        the record before flipping, so resume is idempotent."""
        with _TRACER.start_span("replication.promote", {
            "phase": "promote", "namespace": nb.namespace,
            "notebook": nb.name, "epoch": entry["epoch"],
            "from": entry["from"], "to": entry["to"],
        }) as span:
            if not entry.get("resume"):
                if not self._commit_promotion_record(nb, entry):
                    span.add_event("promote.lost_race", {
                        "epoch": entry["epoch"]})
                    self.metrics.promotions.labels(
                        nb.namespace, PROMOTE_RESULT_LOST_RACE).inc()
                    return
            if self.session is not None:
                self.session.fence(nb.namespace, nb.name, entry["epoch"])
                span.add_event("promote.fenced", {
                    "epoch": entry["epoch"]})
            if not self._commit_promotion_flip(nb, entry):
                self.metrics.promotions.labels(
                    nb.namespace, PROMOTE_RESULT_LOST_RACE).inc()
                return
            duration = 0.0
            if entry.get("disrupted_at") is not None:
                duration = max(
                    self.clock.now() - entry["disrupted_at"], 0.0)
            tid = span.trace_id
            exemplar = {"trace_id": tid} if tid else None
            self.metrics.disruption_recovery_seconds.labels(
                nb.namespace).observe(duration, exemplar=exemplar)
            self.metrics.promotion_duration_seconds.labels(
                nb.namespace).observe(duration, exemplar=exemplar)
            self.metrics.promotions.labels(
                nb.namespace, PROMOTE_RESULT_PROMOTED).inc()
            span.add_event("promote.complete", {
                "epoch": entry["epoch"], "to": entry["to"],
                "seconds": duration})
            self.recorder.event(
                nb.obj, "Normal", EVENT_PRIMARY_PROMOTED,
                "promoted replica %d to primary (epoch %d) after %s on "
                "replica %d; demoted gang rejoins as follower" % (
                    entry["to"], entry["epoch"],
                    entry["reason"] or "disruption", entry["from"]))

    def _commit_promotion_record(self, nb, entry) -> bool:
        """Write-ahead half of the promotion: epoch bump + promotion
        record in ONE status commit, CAS-guarded on the epoch/primary the
        election read — exactly one promoter per epoch can win."""
        committed = {"ok": False}

        def write() -> None:
            committed["ok"] = False
            try:
                live = self.api.get("Notebook", nb.namespace, nb.name)
            except NotFoundError:
                return
            st = live.body.setdefault("status", {})
            rep = copy.deepcopy(st.get("replication") or {})
            if rep.get("epoch", 1) != entry["epoch"] - 1 or \
                    rep.get("primary", 0) != entry["from"]:
                return  # another promoter moved the authority first
            rep["epoch"] = entry["epoch"]
            rep["promotion"] = {
                "epoch": entry["epoch"],
                "from": entry["from"],
                "to": entry["to"],
                "phase": "promoting",
                "reason": entry["reason"],
                "startedAt": self.clock.now_iso(),
            }
            st["replication"] = rep
            self.api.update_status(live)
            committed["ok"] = True

        retry_on_conflict(write)
        return committed["ok"]

    def _commit_promotion_flip(self, nb, entry) -> bool:
        """Completion half: name the new primary and close the record.
        Verifies the committed record is still OURS (epoch + target) —
        the re-read-the-authority-before-acting discipline of
        kube/leader.py FencingToken.verify()."""
        done = {"ok": False}

        def write() -> None:
            done["ok"] = False
            try:
                live = self.api.get("Notebook", nb.namespace, nb.name)
            except NotFoundError:
                return
            st = live.body.setdefault("status", {})
            rep = copy.deepcopy(st.get("replication") or {})
            promo = rep.get("promotion") or {}
            if rep.get("epoch") != entry["epoch"] or \
                    promo.get("to") != entry["to"]:
                return  # superseded by a later promotion
            if promo.get("phase") == "promoted" and \
                    rep.get("primary") == entry["to"]:
                done["ok"] = True  # resume found it already complete
                return
            rep["primary"] = entry["to"]
            promo["phase"] = "promoted"
            promo["completedAt"] = self.clock.now_iso()
            rep["promotion"] = promo
            st["replication"] = rep
            self.api.update_status(live)
            done["ok"] = True

        retry_on_conflict(write)
        return done["ok"]

    # -- verb execution -------------------------------------------------------
    def _execute_restart(self, nb, entry, span, stamp_restore,
                         restart_slice) -> None:
        if entry.get("restamp") and stamp_restore is not None:
            # a dropped restore intent must leave the template too, or the
            # recreated pods would resurrect the retired generation
            stamp_restore(entry["live_name"], entry["idx"])
        span.add_event("slice.restart", {
            "slice": entry["idx"], "sts": entry["live_name"],
            "reason": entry["reason"], "pod": entry["pod"],
            "attempt": entry["attempt"], "backoff_s": entry["delay"],
        })
        self.metrics.slice_restarts.labels(
            nb.namespace, entry["reason"]).inc()
        if entry.get("fallback"):
            # a session store is wired but could not supply a usable
            # checkpoint: account the degraded outcome
            self.metrics.migrations.labels(
                entry.get("trigger") or MIGRATE_TRIGGER_FAILURE,
                MIGRATE_RESULT_FALLBACK).inc()
        self.recorder.event(
            nb.obj, "Normal", EVENT_SLICE_RECOVERY,
            "restarting slice %d (%s): %s is %s (attempt %d/%d, "
            "next backoff %.0fs)" % (
                entry["idx"], entry["live_name"],
                entry["pod"] or "workers", entry["reason"],
                entry["attempt"], self.cfg.recovery_max_attempts,
                entry["delay"]))
        restart_slice(entry["live_name"])

    def _execute_migrate(self, nb, entry, stamp_restore,
                         restart_slice) -> None:
        """The migrate verb, under its own `migrate` phase span: restore
        stamping first (the recreated pods must boot with the restore
        env), then the slice-atomic restart.  The write-ahead
        status.sessionState record already landed before this runs."""
        snap: SnapshotInfo = entry["snap"]
        trigger = entry.get("trigger") or MIGRATE_TRIGGER_FAILURE
        with _TRACER.start_span("migrate", {
            "phase": "migrate", "namespace": nb.namespace,
            "notebook": nb.name, "slice": entry["idx"], "trigger": trigger,
        }) as span:
            span.add_event("migrate.snapshot", {
                "slice": entry["idx"], "generation": snap.generation,
                "digest": snap.digest, "age_s": entry["ckpt_age_s"],
            })
            self.metrics.slice_restarts.labels(
                nb.namespace, REASON_MIGRATE).inc()
            self.metrics.migrations.labels(
                trigger, MIGRATE_RESULT_MIGRATED).inc()
            self.recorder.event(
                nb.obj, "Normal", EVENT_SLICE_MIGRATION,
                "migrating slice %d (%s): %s; restoring checkpoint "
                "generation %d (age %.0fs, attempt %d/%d)" % (
                    entry["idx"], entry["live_name"],
                    entry["reason_detail"], snap.generation,
                    entry["ckpt_age_s"], entry["attempt"],
                    self.cfg.recovery_max_attempts))
            if stamp_restore is not None:
                stamp_restore(entry["live_name"], entry["idx"])
                span.add_event("migrate.restore_stamped", {
                    "sts": entry["live_name"],
                    "generation": snap.generation,
                })
            restart_slice(entry["live_name"])
            span.add_event("slice.restart", {
                "slice": entry["idx"], "sts": entry["live_name"],
                "reason": REASON_MIGRATE, "attempt": entry["attempt"],
                "backoff_s": entry["delay"],
            })

    # -- per-slice decision ---------------------------------------------------
    def _slice_pass(self, nb, idx, live_name, reasons, pending, healthy,
                    trigger, stale_session, recovery, session_state,
                    restarts, events, notes, span, now, *,
                    allow_migrate: bool = True,
                    observe_recovery: bool = True) -> float:
        # `allow_migrate=False` (replicated notebooks) forces the bare
        # restart verb: the checkpoint-delta stream IS the migration, a
        # demoted/failed follower gang just restarts and catches up.
        # `observe_recovery=False` keeps follower-gang repair latency out
        # of notebook_disruption_recovery_seconds — for a replicated
        # notebook only primary recoveries (and promotions) are
        # user-visible disruptions.
        key = str(idx)
        state = recovery.get(key, {})
        session = session_state.get(key, {})

        # an incomplete migration (a worker provably still on the old
        # session survived the restart sweep) re-enters the migrate flow
        # as its own trigger — through the same budget, so a slice that
        # can never complete still exhausts instead of churning
        if stale_session and trigger is None and not reasons and \
                not pending:
            trigger = session.get("trigger") or MIGRATE_TRIGGER_FAILURE
            span.add_event("migrate.incomplete", {
                "slice": idx,
                "generation": session.get("restoreGeneration")})

        # resolve Pending into a disruption only past the schedule deadline
        reason = reasons[0][1] if reasons else None
        pod_name = reasons[0][0] if reasons else ""
        if reason is None and pending:
            since = state.get("pendingSince")
            if not since:
                state["pendingSince"] = self.clock.now_iso()
                recovery[key] = state
                return self.cfg.recovery_pending_deadline_s
            waited = now - parse_iso(since)
            if waited < self.cfg.recovery_pending_deadline_s:
                return self.cfg.recovery_pending_deadline_s - waited
            reason = REASON_PENDING_TIMEOUT
        elif not pending:
            state.pop("pendingSince", None)

        if reason is None and trigger is None:
            if healthy and session.get("phase") == "migrating":
                # the migrated slice came back Ready: the restore is done
                self._migration_restored(nb, idx, session, events, span)
                session_state[key] = session
            if healthy and state:
                self._slice_recovered(nb, idx, state, events, span, now,
                                      observe_recovery=observe_recovery)
                if state:
                    recovery[key] = state
                else:
                    recovery.pop(key, None)
            elif state:
                recovery[key] = state  # pendingSince cleanup above
            return 0.0

        voluntary = reason is None
        if voluntary and not healthy:
            # mid-recreate / not-yet-Ready: neither disrupted nor safely
            # snapshottable — let the slice settle, keep the request
            notes["deferred"] = True
            if state:
                recovery[key] = state
            return self.cfg.recovery_backoff_base_s

        # -- disrupted or voluntarily migrating -------------------------------
        if voluntary:
            span.add_event("migrate.requested", {
                "slice": idx, "sts": live_name, "trigger": trigger})
        else:
            span.add_event("slice.disrupted", {
                "slice": idx, "sts": live_name, "reason": reason,
                "pod": pod_name,
            })
        if state.get("exhausted"):
            # terminal: the budget is spent; an operator action that turns
            # the slice Healthy again (e.g. the restart annotation after a
            # fix) resets it via _slice_recovered
            recovery[key] = state
            return 0.0
        if not voluntary:
            state.setdefault("disruptedAt", self.clock.now_iso())
        state["reason"] = reason if reason is not None else trigger
        attempts = [t for t in state.get("attempts", [])
                    if now - parse_iso(t) < self.cfg.recovery_window_s]
        state["attempts"] = attempts

        until = state.get("backoffUntil")
        if until and now < parse_iso(until):
            remaining = parse_iso(until) - now
            span.add_event("recovery.backoff_wait", {
                "slice": idx, "remaining_s": remaining})
            recovery[key] = state
            if voluntary:
                notes["deferred"] = True
            return remaining

        if len(attempts) >= self.cfg.recovery_max_attempts:
            state["exhausted"] = True
            recovery[key] = state
            span.add_event("recovery.exhausted", {
                "slice": idx, "attempts": len(attempts),
                "reason": state["reason"]})
            events.append((
                "Warning", EVENT_RECOVERY_EXHAUSTED,
                "slice %d (%s) spent its restart budget (%d restarts in "
                "%.0fs) on %s; manual intervention required" % (
                    idx, live_name, len(attempts),
                    self.cfg.recovery_window_s, state["reason"])))
            logger.error(
                "recovery exhausted for %s/%s slice %d after %d attempts "
                "(%s)", nb.namespace, nb.name, idx, len(attempts),
                state["reason"])
            return 0.0

        # verb decision: migrate when a usable checkpoint can be secured
        use_session = self.session is not None and allow_migrate
        snap = None
        ckpt_age = 0.0
        if use_session:
            snap, ckpt_age = self._secure_checkpoint(nb, idx, span, now)
        if snap is None and voluntary:
            # a healthy session is never torn down without its state in
            # hand — skip, tell the operator, consume the request
            events.append((
                "Warning", EVENT_MIGRATION_SKIPPED,
                "slice %d (%s): voluntary migration (%s) skipped — no "
                "session checkpoint within %.0fs" % (
                    idx, live_name, trigger,
                    self.cfg.checkpoint_max_age_s)))
            self.metrics.migrations.labels(
                trigger, MIGRATE_RESULT_SKIPPED).inc()
            if state:
                recovery[key] = state
            return 0.0

        delay = min(
            self.cfg.recovery_backoff_base_s * (2 ** len(attempts)),
            self.cfg.recovery_backoff_max_s)
        stamp = self.clock.now_iso()
        attempts.append(stamp)
        state["lastRestartTime"] = stamp
        state["backoffUntil"] = _iso_at(now + delay)
        recovery[key] = state
        restamp = False
        if snap is None and session.get("restoreGeneration") is not None:
            # the bare fallback restarts COLD: retire the old restore
            # intent (write-ahead) so the recreated pods don't resurrect
            # an ancient session generation
            session_state.pop(key, None)
            session = {}
            restamp = True
        entry = {
            "idx": idx, "live_name": live_name,
            "reason": state["reason"], "pod": pod_name,
            "attempt": len(attempts), "delay": delay,
            "verb": REASON_MIGRATE if snap is not None else "restart",
            "trigger": (trigger if voluntary else MIGRATE_TRIGGER_FAILURE)
            if use_session else None,
            "snap": snap, "ckpt_age_s": ckpt_age,
            "restamp": restamp,
            "fallback": snap is None and use_session,
            "reason_detail": ("voluntary %s" % trigger) if voluntary
            else "%s is %s" % (pod_name or "workers", state["reason"]),
        }
        if snap is not None:
            # write-ahead restore intent: mirrored into status.sessionState
            # before any pod dies, so failover resumes — not repeats — the
            # restore
            session.update({
                "restoreGeneration": snap.generation,
                "restoreUri": snap.uri,
                "digest": snap.digest,
                "savedAt": _iso_at(snap.saved_at),
                "trigger": entry["trigger"],
                "phase": "migrating",
                "migratedAt": self.clock.now_iso(),
            })
            session.pop("restoredAt", None)
            session_state[key] = session
        restarts.append(entry)
        return delay

    def _secure_checkpoint(self, nb: Notebook, idx: int, span,
                           now: float) -> tuple[Optional[SnapshotInfo],
                                                float]:
        """Best checkpoint for a migrate decision: a just-in-time final
        snapshot when the slice can still flush (the store dispatches to
        the data plane), else the freshest stored snapshot within
        CHECKPOINT_MAX_AGE_S.  Returns (snapshot, age_s) — (None, 0) means
        the migrate verb is unavailable and restart is the fallback."""
        final = self.session.request_final_snapshot(
            nb.namespace, nb.name, idx)
        if final is not None:
            self.metrics.checkpoint_snapshots.labels(
                nb.namespace, TRIGGER_FINAL).inc()
            self.metrics.checkpoint_age_seconds.labels(
                nb.namespace).observe(0.0)
            span.add_event("checkpoint.final", {
                "slice": idx, "generation": final.generation})
            return final, 0.0
        latest = self.session.latest(nb.namespace, nb.name, idx)
        if latest is None:
            span.add_event("checkpoint.missing", {"slice": idx})
            return None, 0.0
        age = max(now - latest.saved_at, 0.0)
        self.metrics.checkpoint_age_seconds.labels(
            nb.namespace).observe(age)
        if age <= self.cfg.checkpoint_max_age_s:
            span.add_event("checkpoint.fresh", {
                "slice": idx, "generation": latest.generation,
                "age_s": age})
            return latest, age
        span.add_event("checkpoint.stale", {
            "slice": idx, "generation": latest.generation, "age_s": age})
        return None, age

    def _migration_restored(self, nb, idx, session, events, span) -> None:
        """The migrated slice reads Healthy: flip the write-ahead record to
        its terminal phase exactly once (failover-safe — a second manager
        seeing phase=='restored' does nothing)."""
        session["phase"] = "restored"
        session["restoredAt"] = self.clock.now_iso()
        span.add_event("migrate.restored", {
            "slice": idx, "generation": session.get("restoreGeneration")})
        self.metrics.migrations.labels(
            session.get("trigger") or MIGRATE_TRIGGER_FAILURE,
            MIGRATE_RESULT_RESTORED).inc()
        events.append((
            "Normal", EVENT_MIGRATION_COMPLETE,
            "slice %d restored session checkpoint generation %s after "
            "migration" % (idx, session.get("restoreGeneration"))))

    def _slice_recovered(self, nb, idx, state, events, span, now, *,
                         observe_recovery: bool = True) -> None:
        """Disruption over: observe the detection→Healthy latency once and
        drop the transient fields.  Attempt stamps stay and age out by the
        sliding window (the flap guard) — except after exhaustion, where a
        Healthy slice means an operator fixed it and earns a fresh
        budget.  `observe_recovery=False` (follower gangs of a replicated
        notebook) heals the bookkeeping without charging the user-facing
        disruption histogram."""
        if observe_recovery and state.get("disruptedAt"):
            duration = max(now - parse_iso(state["disruptedAt"]), 0.0)
            tid = span.trace_id
            self.metrics.disruption_recovery_seconds.labels(
                nb.namespace).observe(
                    duration, exemplar={"trace_id": tid} if tid else None)
            span.add_event("recovery.healthy", {
                "slice": idx, "seconds": duration})
        if state.pop("exhausted", False):
            state.pop("attempts", None)
            state.pop("backoffUntil", None)
            events.append((
                "Normal", EVENT_RECOVERY_RESTORED,
                "slice %d is Healthy again after exhaustion; restart "
                "budget reset" % idx))
        # backoffUntil deliberately survives healing: a slice that flaps
        # (fail -> restart -> Healthy -> fail) must still wait out the
        # armed backoff before the next restart, or flapping defeats the
        # exponential spacing; it expires on its own
        for field in ("disruptedAt", "reason", "pendingSince"):
            state.pop(field, None)
        if not state.get("attempts"):
            state.pop("attempts", None)
            state.pop("lastRestartTime", None)
            state.pop("backoffUntil", None)

    # -- persistence ----------------------------------------------------------
    def _write_bookkeeping(self, nb: Notebook, recovery: dict,
                           exhausted: Optional[list[str]] = None,
                           session_state: Optional[dict] = None,
                           replication: Optional[dict] = None,
                           skip_if_unchanged: Optional[tuple] = None) -> None:
        """Persist status.sliceRecovery + status.sessionState (+ the
        follower-freshness half of status.replication, and the
        RecoveryExhausted condition) with conflict retry.  Runs BEFORE any
        pod delete of the same pass, so the attempt charge and the restore
        intent are crash-safe.  `session_state` None leaves
        status.sessionState untouched (the Stopped-cleanup path drops only
        the recovery budget — the pre-cull checkpoint record must
        survive); `replication` None likewise.
        `skip_if_unchanged=(prev_recovery, prev_session[,
        prev_replication])` makes an unchanged write a no-op — the check
        lives HERE, not at the call site, so the caller's call dominates
        its pod deletes on the CFG (ci/analyzers/write_ahead.py)."""
        if skip_if_unchanged is not None and \
                recovery == skip_if_unchanged[0] and \
                session_state == skip_if_unchanged[1] and \
                (len(skip_if_unchanged) < 3 or
                 replication == skip_if_unchanged[2]):
            return
        exhausted = exhausted or []

        def write() -> None:
            try:
                live = self.api.get("Notebook", nb.namespace, nb.name)
            except NotFoundError:
                return
            st = live.body.setdefault("status", {})
            if recovery:
                st["sliceRecovery"] = copy.deepcopy(recovery)
            else:
                st.pop("sliceRecovery", None)
            if session_state is not None:
                if session_state:
                    st["sessionState"] = copy.deepcopy(session_state)
                else:
                    st.pop("sessionState", None)
            if replication is not None:
                # epoch-regression guard: a promoter (this manager or a
                # peer) may have bumped the authority between our read
                # and this write — never let the freshness mirror roll
                # back the epoch/primary/promotion record it rode in on
                live_rep = st.get("replication") or {}
                if live_rep.get("epoch", 0) <= replication.get("epoch", 1):
                    merged = copy.deepcopy(live_rep)
                    merged.update(copy.deepcopy(replication))
                    st["replication"] = merged
            conds = list(st.get("conditions") or [])
            existing = next(
                (c for c in conds
                 if c.get("type") == CONDITION_RECOVERY_EXHAUSTED), None)
            if exhausted:
                if existing is None or existing.get("status") != "True":
                    conds = [c for c in conds
                             if c.get("type") != CONDITION_RECOVERY_EXHAUSTED]
                    conds.append({
                        "type": CONDITION_RECOVERY_EXHAUSTED,
                        "status": "True",
                        "reason": "RestartBudgetSpent",
                        "message": "slice(s) %s spent the restart budget "
                                   "(%d attempts within %.0fs)" % (
                                       ",".join(exhausted),
                                       self.cfg.recovery_max_attempts,
                                       self.cfg.recovery_window_s),
                        "lastTransitionTime": self.clock.now_iso(),
                    })
            elif existing is not None:
                conds = [c for c in conds
                         if c.get("type") != CONDITION_RECOVERY_EXHAUSTED]
            st["conditions"] = conds
            self.api.update_status(live)

        retry_on_conflict(write)

    def _clear_migrate_annotation(self, nb: Notebook) -> None:
        def clear() -> None:
            try:
                live = self.api.get("Notebook", nb.namespace, nb.name)
            except NotFoundError:
                return
            if C.ANNOTATION_MIGRATE in live.metadata.annotations:
                live.metadata.annotations.pop(C.ANNOTATION_MIGRATE, None)
                self.api.update(live)

        retry_on_conflict(clear)


def _merge_requeue(current: float, hint: float) -> float:
    """Combine requeue-after hints: 0 means 'none'; otherwise soonest
    wins."""
    if hint <= 0:
        return current
    if current <= 0:
        return hint
    return min(current, hint)


def _iso_at(t: float) -> str:
    import time as _time

    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(t))


__all__ = [
    "MIGRATE_RESULT_FALLBACK",
    "MIGRATE_RESULT_MIGRATED",
    "MIGRATE_RESULT_RESTORED",
    "MIGRATE_RESULT_SKIPPED",
    "MIGRATE_TRIGGER_DEFRAG",
    "MIGRATE_TRIGGER_DRAIN",
    "MIGRATE_TRIGGER_FAILURE",
    "MIGRATE_TRIGGER_NODE_DRAIN",
    "PENDING",
    "PROMOTE_RESULT_LOST_RACE",
    "PROMOTE_RESULT_NO_CANDIDATE",
    "PROMOTE_RESULT_PROMOTED",
    "REASON_CRASH_LOOP",
    "REASON_MIGRATE",
    "REASON_NODE_GONE",
    "REASON_PENDING_TIMEOUT",
    "REASON_POD_FAILED",
    "RecoveryEngine",
    "SliceRestartError",
    "VERB_PROMOTE",
    "classify_worker",
    "node_drained",
]
