"""Topology-aware TPU-slice scheduler + warm-pool autoscaler.

Placement used to be whatever the fake kubelet's first-fit loop did: no
notion of slices, so a multi-host slice's workers could land wherever
capacity happened to be free, and every notebook start paid the cold
slice-provision path.  This module owns placement and capacity instead
(ROADMAP item 4; NotebookOS arXiv:2503.20591 shows interactive platforms
live or die on notebook-ready latency, and the RL-scheduler line of work
arXiv:2601.13579 motivates keeping the policy pluggable behind a
deterministic cost function):

- **Gang placement intent.**  `SliceScheduler` reconciles Notebooks and
  writes an all-or-nothing placement intent
  (`notebooks.kubeflow.org/placement`, JSON: slice id -> node-pool
  assignment) BEFORE any workload StatefulSet exists — the notebook
  controller gang-gates rendering on it, so a half-placed slice can never
  wedge: either every slice of the notebook has a pool or no pod binds.
  The rendered StatefulSet turns each assignment into a
  `cloud.google.com/gke-nodepool` nodeSelector, which co-locates the
  whole gang on one pool.

- **PlacementPolicy.**  The placement decision itself sits behind a small
  interface; `CostFunctionPolicy` is the deterministic default — pack
  multi-host gangs onto the feasible pool with the least leftover
  capacity (best-fit, fights fragmentation), spread single-host notebooks
  onto the node with the most free chips — so a learned policy can drop
  in without touching claim bookkeeping.

- **Warm pool.**  One cluster-scoped `TPUWarmPool` object per
  accelerator/topology shape tracks pre-provisioned slices through
  Provisioning -> Ready -> Claimed.  A new Notebook *claims* a Ready
  slice (O(reconcile) to first pod instead of a cold provision of
  WARMPOOL_PROVISION_S); a miss provisions a dedicated slice on demand
  (reservation written ahead, so a crash mid-flight resumes instead of
  double-provisioning).  All claim/release state lives in the pool
  object's status — manager crash or leader failover changes nothing.

- **Culling -> reclamation.**  A culled/Stopped notebook's claimed slices
  drain back into the pool as Ready (nodes stay provisioned — the
  capacity is resold to the next claim) rather than being destroyed.
  Release waits for `sliceHealth == "Stopped"`, which by construction
  postdates the checkpoint-on-cull handshake: a slice is never reclaimed
  while a final snapshot may still be flushing.

- **Autoscaler.**  `WarmPoolController` drives each pool toward a target
  hit-rate: the target grows by the misses observed since the last pass
  (bounded by WARMPOOL_MAX_SIZE) and decays back toward WARMPOOL_SIZE
  one step at a time while the cumulative hit rate holds above
  WARMPOOL_TARGET_HIT_RATE and idle Ready slices exceed the target.
  Excess idle slices are retired (deprovisioned) deterministically.

Everything is timed off the injected Clock, so the whole subsystem is
FakeClock-exact: provisioning latency is a `readyAt` deadline plus a
requeue_after, never a sleep.
"""

from __future__ import annotations

import copy
import json
import logging
from dataclasses import dataclass
from typing import Optional, Protocol

from ..api.types import PRIORITY_DEFAULT, PRIORITY_RANK, Notebook
from ..kube import (
    AlreadyExistsError,
    ApiServer,
    EventRecorder,
    EventType,
    InvalidError,
    KubeObject,
    Manager,
    ObjectMeta,
    Request,
    Result,
    WatchSpec,
    parse_quantity,
    retry_on_conflict,
)
from ..tpu.topology import SliceShape, TopologyError, resolve
from ..utils import tracing
from ..utils.clock import Clock
from ..utils.config import CoreConfig
from . import constants as C
from .metrics import NotebookMetrics, placement_chips

logger = logging.getLogger("kubeflow_tpu.scheduler")

# the `schedule` phase span parents onto the manager's per-attempt
# reconcile root via the shared context stack (flight-recorder visible)
_TRACER = tracing.get_tracer("kubeflow_tpu.core.scheduler")

# schedule-attempt outcomes — bounded set, they label
# notebook_schedule_attempts_total{result}
SCHEDULE_PLACED = "placed"
SCHEDULE_NOOP = "noop"
SCHEDULE_WAIT = "wait-provisioning"
SCHEDULE_RELEASED = "released"
SCHEDULE_QUEUED = "queued"

# warm-pool claim outcomes — bounded set, they label
# notebook_warmpool_hits_total{result}
CLAIM_HIT = "hit"            # claimed a pre-provisioned Ready slice
CLAIM_MISS = "miss"          # cold path: dedicated provision reserved
CLAIM_BYPASS = "bypass"      # placed on pre-existing unmanaged capacity

# event reasons (kubectl describe notebook)
EVENT_SCHEDULED = "SliceScheduled"
EVENT_RELEASED = "SliceReleased"


def pool_object_name(accelerator: str, topology: str) -> str:
    return f"warmpool-{accelerator}-{topology}"


def parse_warmpool_shapes(shapes: str) -> list[tuple[str, str]]:
    """WARMPOOL_SHAPES="v5e:4x4,v5p:2x2x2" -> [(accelerator, topology)].
    Malformed entries are skipped (config must never take the manager
    down), duplicates collapse."""
    out: list[tuple[str, str]] = []
    for part in shapes.split(","):
        part = part.strip()
        if not part:
            continue
        accel, _, topo = part.partition(":")
        if not accel or not topo:
            continue
        try:
            resolve(accel, topo)
        except TopologyError:
            logger.warning("WARMPOOL_SHAPES: skipping malformed %r", part)
            continue
        if (accel, topo) not in out:
            out.append((accel, topo))
    return out


def placement_of(annotations: dict) -> dict:
    """The placement intent's slice map ({"<id>": {"pool": ..,
    "nodes": [..]}}) from CR annotations; {} when absent/malformed."""
    raw = annotations.get(C.ANNOTATION_PLACEMENT)
    if not raw:
        return {}
    try:
        doc = json.loads(raw)
    except ValueError:
        return {}
    slices = doc.get("slices") if isinstance(doc, dict) else None
    return slices if isinstance(slices, dict) else {}


def placement_covers(nb: Notebook, num_slices: int) -> bool:
    """True when the intent assigns a pool to EVERY slice — the gang
    gate the notebook controller holds STS rendering on."""
    slices = placement_of(nb.metadata.annotations)
    return all(
        (slices.get(str(i)) or {}).get("pool")
        for i in range(num_slices)
    )


# -- tenancy policy ------------------------------------------------------------
def tenant_policy(quota_obj: Optional[KubeObject], namespace: str) -> dict:
    """Effective tenancy policy for one namespace: the TenantQuota spec's
    per-tenant entry over its spec.defaults over the module defaults.
    chip_quota <= 0 means unlimited; weight is clamped positive so the
    fair-share division is always defined."""
    out = {"chip_quota": 0.0, "weight": 1.0, "priority": PRIORITY_DEFAULT}
    if quota_obj is None:
        return out
    spec = quota_obj.spec
    defaults = spec.get("defaults") or {}
    tenant = (spec.get("tenants") or {}).get(namespace) or {}

    def _num(key: str, fallback: float) -> float:
        # layered: a malformed per-tenant value falls back to the
        # cluster default, never to "unlimited" — a typo in one tenant's
        # entry must not hand that tenant the whole fleet
        for src in (tenant, defaults):
            if key in src:
                try:
                    return float(src[key] or 0.0)
                except (TypeError, ValueError):
                    continue
        return fallback

    out["chip_quota"] = _num("chipQuota", 0.0)
    out["weight"] = max(_num("weight", 1.0), 1e-9)
    merged = dict(defaults)
    merged.update(tenant)
    if merged.get("priority") in PRIORITY_RANK:
        out["priority"] = merged["priority"]
    return out


def resolve_priority(nb: Notebook,
                     quota_obj: Optional[KubeObject]) -> str:
    """A notebook's effective priority class: explicit spec.priority
    wins, else the tenant default from TenantQuota, else "standard"."""
    p = nb.priority
    if p in PRIORITY_RANK:
        return p
    return tenant_policy(quota_obj, nb.namespace)["priority"]


def rank_of(priority: Optional[str]) -> int:
    return PRIORITY_RANK.get(priority or "",
                             PRIORITY_RANK[PRIORITY_DEFAULT])


def gang_chips(obj: KubeObject) -> float:
    """Total chips one notebook's gangs occupy when placed: shape chips x
    slices x replicas (0.0 for CPU notebooks / unresolvable shapes)."""
    rep = (obj.spec.get("replication") or {}).get("replicas")
    try:
        replicas = max(int(rep), 1) if rep else 1
    except (TypeError, ValueError):
        replicas = 1
    return placement_chips(obj) * replicas


def queued_info(annotations: dict) -> dict:
    """The queued annotation's JSON body ({since, priority, reason});
    {} when absent/malformed."""
    raw = (annotations or {}).get(C.ANNOTATION_QUEUED)
    if not raw:
        return {}
    try:
        doc = json.loads(raw)
    except ValueError:
        return {}
    return doc if isinstance(doc, dict) else {}


def _mutate_queue_stamp(api, namespace: str, name: str, fn) -> bool:
    """Annotation-only RMW for the admission-queue stamp; True when the
    write actually changed something.

    Deliberately a module-level helper taking the api as a parameter:
    ci/analyzers/write_ahead.py treats `self.api.update` inside
    _place/_release as the intent write that must trail the pool claim
    commit.  The queued stamp is NOT an intent — a gang the admission
    gate parks holds no claims, so there is no crash-recovery record to
    order against — and keeping it out of the methods' call graphs keeps
    the analyzer's destructive set precise instead of allowlisted away.
    """
    changed = [False]

    def stamp_rmw() -> None:
        live = api.get("Notebook", namespace, name)
        before = dict(live.metadata.annotations)
        fn(live.metadata.annotations)
        if live.metadata.annotations != before:
            api.update(live)
            changed[0] = True

    retry_on_conflict(stamp_rmw)
    return changed[0]


# -- placement policy ----------------------------------------------------------
@dataclass(frozen=True)
class NodeCapacity:
    """One schedulable node as the policy sees it: its pool membership and
    the TPU chips still free after bound pods and standing reservations."""

    name: str
    pool: str
    free_chips: float
    total_chips: float


@dataclass(frozen=True)
class GangPlacement:
    """All-or-nothing verdict: the pool the gang lands on plus the exact
    node set (ordinal-ordered), or nothing at all."""

    pool: str
    nodes: tuple[str, ...]


class PlacementPolicy(Protocol):
    """The pluggable placement decision (a learned policy drops in here).
    Must be deterministic for a given inventory: the scheduler replays it
    on retries and across failovers and expects the same answer."""

    def place(self, shape: SliceShape,
              nodes: list[NodeCapacity]) -> Optional[GangPlacement]: ...


class CostFunctionPolicy:
    """Deterministic cost-function placement.

    Multi-host gangs: feasible pools are those with >= num_hosts nodes
    each fitting chips_per_host; the chosen pool minimizes leftover free
    chips after placement (best-fit packing — keeps big contiguous pools
    free for big gangs), tie-broken by pool name; within the pool the
    fullest fitting nodes are taken first (hole-filling).  Never returns
    a partial gang.

    Single-host notebooks: spread — the node with the MOST free chips
    wins (tie-break by name), so interactive singles distribute instead
    of stacking onto one host.
    """

    def place(self, shape: SliceShape,
              nodes: list[NodeCapacity]) -> Optional[GangPlacement]:
        need = float(shape.chips_per_host)
        fitting = [n for n in nodes if n.free_chips >= need]
        if shape.num_hosts == 1:
            if not fitting:
                return None
            best = sorted(fitting, key=lambda n: (-n.free_chips, n.name))[0]
            return GangPlacement(best.pool, (best.name,))
        by_pool: dict[str, list[NodeCapacity]] = {}
        for n in fitting:
            by_pool.setdefault(n.pool, []).append(n)
        candidates: list[tuple[float, str, tuple[str, ...]]] = []
        for pool, members in sorted(by_pool.items()):
            if len(members) < shape.num_hosts:
                continue
            chosen = sorted(members, key=lambda n: (n.free_chips, n.name))
            chosen = chosen[: shape.num_hosts]
            leftover = sum(n.free_chips for n in members) \
                - shape.num_hosts * need
            candidates.append(
                (leftover, pool, tuple(n.name for n in chosen)))
        if not candidates:
            return None
        _, pool, names = min(candidates)
        return GangPlacement(pool, names)


# -- slice scheduler controller ------------------------------------------------
class SliceScheduler:
    """Owns the Notebook -> capacity binding: warm claims, cold
    provisioning reservations, bypass placement on unmanaged capacity,
    and culling->reclamation release.  All bookkeeping rides the shape's
    TPUWarmPool status (one object per shape, optimistic concurrency
    serializes racing claims), and the final intent is the placement
    annotation — written only once EVERY slice has an assignment."""

    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        metrics: NotebookMetrics,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
        cache=None,
        policy: Optional[PlacementPolicy] = None,
    ):
        self.api = api
        self.cfg = cfg
        self.metrics = metrics
        self.recorder = recorder or EventRecorder(api, "slice-scheduler")
        self.clock = clock or Clock()
        self.cache = cache
        self.policy = policy or CostFunctionPolicy()
        # PreemptionEngine attached by setup_scheduler: consulted when an
        # admitted gang still cannot place (cold-provision wait)
        self.preemption = None

    def reconcile(self, req: Request) -> Result:
        if self.cache is not None:
            obj = self.cache.get("Notebook", req.namespace, req.name)
        else:
            obj = self.api.try_get("Notebook", req.namespace, req.name)
        if obj is None:
            return Result()  # deletion: the pool controller GCs claims
        nb = Notebook(obj)
        # lifecycle ledger identity: scheduler attempts land on the same
        # (ns, name, generation) stage ledger as the notebook controller's
        _TRACER.current_span().set_attribute(
            "generation", int(obj.metadata.generation or 1))
        tpu = nb.tpu
        if tpu is None or obj.metadata.deletion_timestamp is not None:
            return Result()
        try:
            shape = tpu.validate()
        except InvalidError:
            return Result()  # the validation webhook's problem, not ours
        with _TRACER.start_span(
            "schedule",
            {"phase": "schedule", "namespace": req.namespace,
             "notebook": req.name},
        ) as span:
            if C.STOP_ANNOTATION in nb.metadata.annotations:
                return self._release(nb, shape, span)
            # a replicated notebook (spec.replication) schedules one gang
            # per replica x slice — flat gang index g = replica *
            # num_slices + slice, matching the replica-major live_names
            # order the notebook controller renders
            rep = nb.replication
            return self._place(nb, tpu.slices, shape, span,
                               replicas=rep.replicas if rep else 1,
                               anti_affine=bool(rep and rep.anti_affine))

    # -- placement -------------------------------------------------------------
    def _place(self, nb: Notebook, num_slices: int, shape: SliceShape,
               span, replicas: int = 1,
               anti_affine: bool = False) -> Result:
        """Place every gang of the notebook: `num_slices` gangs per
        replica, `replicas * num_slices` total, flat gang index.  With
        `anti_affine` (replicated notebooks), replica R's gangs must land
        on node pools disjoint from every OTHER replica's — one pool
        failure can then never take the primary and its standby
        together.  Slices within one replica may share a pool, exactly
        as before."""
        key = f"{nb.namespace}/{nb.name}"
        total_gangs = num_slices * max(replicas, 1)
        # tenancy admission gate: BEFORE any claim is written, a gang
        # over its tenant's quota / weighted fair share — or behind a
        # higher-scoring queued gang — parks as Queued instead of
        # claiming capacity
        gate = self._admission(nb, shape, total_gangs, span)
        if gate is not None:
            return gate
        out: dict = {}

        def replica_of(gang: int) -> int:
            return gang // num_slices

        def attempt() -> None:
            # the eviction fence, re-checked on EVERY conflict retry: an
            # eviction that committed AFTER admission passed must not let
            # this stale placement run finish — its retry would re-claim
            # the just-freed slices and resurrect the victim on capacity
            # its beneficiary was promised
            live_nb = self.api.try_get("Notebook", nb.namespace, nb.name)
            if live_nb is None or self._pending_eviction(key) or \
                    self._preempt_fence_holds(
                        live_nb.metadata.annotations or {}):
                out.clear()
                out["fenced"] = True
                return
            out.pop("fenced", None)
            live = self._ensure_pool(shape)
            before = copy.deepcopy(live.body.get("status") or {})
            st = copy.deepcopy(before)
            st.setdefault("target", self.cfg.warmpool_size)
            st.setdefault("seq", 0)
            for k in ("hits", "misses", "bypass"):
                st.setdefault(k, 0)
            slices = st.setdefault("slices", {})
            claims = {CLAIM_HIT: 0, CLAIM_MISS: 0, CLAIM_BYPASS: 0}
            assignments: dict[int, str] = {}
            waiting = False

            # adopt claims/reservations already held (crash recovery: the
            # claim is written ahead of the annotation, so a scheduler
            # that died in between finds and finishes its own work)
            for sid in sorted(slices):
                e = slices[sid]
                if e.get("claimedBy") != key:
                    continue
                idx = e.get("claimedSlice")
                if isinstance(idx, int) and 0 <= idx < total_gangs \
                        and idx not in assignments:
                    assignments[idx] = sid
                else:
                    self._release_entry(slices, sid)  # stale (scale-in)

            # pools each replica already occupies (adopted claims count:
            # the anti-affinity verdict must survive crash recovery)
            pools_by_replica: dict[int, set[str]] = {}
            for idx, sid in assignments.items():
                pool = slices[sid].get("pool", "")
                if pool:
                    pools_by_replica.setdefault(
                        replica_of(idx), set()).add(pool)

            def foreign_pools(gang: int) -> set[str]:
                if not anti_affine:
                    return set()
                r = replica_of(gang)
                return {p for rr, ps in pools_by_replica.items()
                        if rr != r for p in ps}

            for idx in range(total_gangs):
                sid = assignments.get(idx)
                if sid is not None:
                    e = slices[sid]
                    if e.get("state") == C.WARMSLICE_PROVISIONING:
                        # a Ready slice freed since this cold reservation
                        # was written (release, or a preemption run for
                        # this very gang) serves the gang NOW: cancel the
                        # not-yet-provisioned reservation, claim the Ready
                        # slice.  No hit/miss accounting — the miss was
                        # already counted when the reservation was made.
                        swap = next(
                            (s for s in sorted(slices)
                             if slices[s].get("state") == C.WARMSLICE_READY
                             and not slices[s].get("claimedBy")
                             and not slices[s].get("external")
                             and slices[s].get("pool", "")
                             not in foreign_pools(idx)),
                            None)
                        if swap is not None:
                            del slices[sid]
                            slices[swap].update({
                                "state": C.WARMSLICE_CLAIMED,
                                "claimedBy": key,
                                "claimedSlice": idx,
                            })
                            assignments[idx] = swap
                            pools_by_replica.setdefault(
                                replica_of(idx), set()).add(
                                    slices[swap].get("pool", ""))
                            continue
                        # no Ready slice — but UNMANAGED capacity may have
                        # freed since the reservation was written (a
                        # bypass-placed victim's external claim vanishes
                        # on release): re-try bypass so a preemption run
                        # for this gang hands the chips over NOW instead
                        # of waiting out the provision timer.  Same
                        # no-accounting rule as the Ready swap.
                        inventory = [
                            n for n in self._inventory(shape, st)
                            if n.pool not in foreign_pools(idx)]
                        gp = self.policy.place(shape, inventory)
                        if gp is not None:
                            del slices[sid]
                            st["seq"] += 1
                            nsid = f"ws-{st['seq']:04d}"
                            slices[nsid] = {
                                "state": C.WARMSLICE_CLAIMED,
                                "external": True,
                                "pool": gp.pool,
                                "nodes": list(gp.nodes),
                                "claimedBy": key,
                                "claimedSlice": idx,
                            }
                            assignments[idx] = nsid
                            pools_by_replica.setdefault(
                                replica_of(idx), set()).add(gp.pool)
                        else:
                            waiting = True
                    elif e.get("state") == C.WARMSLICE_READY:
                        e["state"] = C.WARMSLICE_CLAIMED
                    continue
                excluded = foreign_pools(idx)
                # warm claim: lowest-id Ready unclaimed pool slice on a
                # pool no other replica occupies
                cand = next(
                    (s for s in sorted(slices)
                     if slices[s].get("state") == C.WARMSLICE_READY
                     and not slices[s].get("claimedBy")
                     and not slices[s].get("external")
                     and slices[s].get("pool", "") not in excluded),
                    None)
                if cand is not None:
                    slices[cand].update({
                        "state": C.WARMSLICE_CLAIMED,
                        "claimedBy": key,
                        "claimedSlice": idx,
                    })
                    assignments[idx] = cand
                    pools_by_replica.setdefault(
                        replica_of(idx), set()).add(
                            slices[cand].get("pool", ""))
                    st["hits"] += 1
                    claims[CLAIM_HIT] += 1
                    continue
                # bypass: cost-function placement on pre-existing capacity
                # outside any warm pool (and outside other replicas' pools)
                inventory = [n for n in self._inventory(shape, st)
                             if n.pool not in excluded]
                gp = self.policy.place(shape, inventory)
                if gp is not None:
                    st["seq"] += 1
                    sid = f"ws-{st['seq']:04d}"
                    slices[sid] = {
                        "state": C.WARMSLICE_CLAIMED,
                        "external": True,
                        "pool": gp.pool,
                        "nodes": list(gp.nodes),
                        "claimedBy": key,
                        "claimedSlice": idx,
                    }
                    assignments[idx] = sid
                    pools_by_replica.setdefault(
                        replica_of(idx), set()).add(gp.pool)
                    st["bypass"] += 1
                    claims[CLAIM_BYPASS] += 1
                    continue
                # cold path: reserve a dedicated slice, provisioned by the
                # WarmPoolController once readyAt passes (the generated
                # pool name is unique per reservation, so cold replicas
                # are anti-affine by construction)
                st["seq"] += 1
                sid = f"ws-{st['seq']:04d}"
                slices[sid] = {
                    "state": C.WARMSLICE_PROVISIONING,
                    "pool": "warm-%s-%s-%04d" % (
                        shape.accelerator.name, shape.topology, st["seq"]),
                    "readyAt": self.clock.now()
                    + self.cfg.warmpool_provision_s,
                    "claimedBy": key,
                    "claimedSlice": idx,
                }
                assignments[idx] = sid
                pools_by_replica.setdefault(
                    replica_of(idx), set()).add(slices[sid]["pool"])
                st["misses"] += 1
                claims[CLAIM_MISS] += 1
                waiting = True

            if st != before:
                live.status = st
                self.api.update_status(live)
            out.update(waiting=waiting, assignments=assignments,
                       slices=copy.deepcopy(slices), claims=claims)

        retry_on_conflict(attempt)
        if out.get("fenced"):
            span.add_event("schedule.preemption_wait", {})
            return Result(
                requeue_after=max(self.cfg.queue_requeue_s, 1.0))

        for result, n in out["claims"].items():
            if n:
                self.metrics.warmpool_hits.labels(result).inc(n)
        if out["waiting"]:
            span.add_event("schedule.wait", {
                "reason": "provisioning",
                "slices": len(out["assignments"])})
            self._count(SCHEDULE_WAIT)
            if self.preemption is not None:
                # an admitted gang stuck on cold provisioning: the
                # preemption engine may free lower-priority checkpointed
                # capacity instead — the freed Ready slices are claimed
                # by the reservation-upgrade path on the next pass (the
                # pool watch wakes us as soon as the eviction commits)
                shortfall = sum(
                    1 for sid in out["assignments"].values()
                    if out["slices"][sid].get("state")
                    == C.WARMSLICE_PROVISIONING) * shape.chips
                self.preemption.maybe_preempt(nb, shape, shortfall, span)
            # the TPUWarmPool watch wakes us the moment the reservation
            # turns Ready; the requeue is a safety net, not the signal
            return Result(
                requeue_after=max(self.cfg.warmpool_provision_s, 1.0))

        intent = {"v": 1, "slices": {}}
        for idx in range(total_gangs):
            e = out["slices"][out["assignments"][idx]]
            entry = {"pool": e["pool"]}
            if e.get("nodes"):
                entry["nodes"] = list(e["nodes"])
            intent["slices"][str(idx)] = entry
        encoded = json.dumps(intent, sort_keys=True, separators=(",", ":"))
        wrote = [False]
        dequeued: dict = {}

        def write_intent() -> None:
            live = self.api.get("Notebook", nb.namespace, nb.name)
            if live.metadata.annotations.get(
                    C.ANNOTATION_PLACEMENT) == encoded:
                return
            # placement retires the queue membership in the same write —
            # the queue-wait clock stops exactly when the intent lands
            dequeued.update(queued_info(live.metadata.annotations))
            live.metadata.annotations.pop(C.ANNOTATION_QUEUED, None)
            live.metadata.annotations[C.ANNOTATION_PLACEMENT] = encoded
            self.api.update(live)
            wrote[0] = True

        retry_on_conflict(write_intent)
        if wrote[0]:
            # time-to-placement by priority: queue wait off the queued
            # stamp (0 for gangs that never queued, so the distribution
            # covers every placement and its p99 is the SLO objective)
            wait = 0.0
            since = dequeued.get("since")
            if isinstance(since, (int, float)):
                wait = max(self.clock.now() - float(since), 0.0)
            pr = dequeued.get("priority")
            if pr not in PRIORITY_RANK:
                pr = resolve_priority(nb, self.api.try_get(
                    C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME))
            tid = span.trace_id
            self.metrics.queue_wait_seconds.labels(pr).observe(
                wait, exemplar={"trace_id": tid} if tid else None)
            span.add_event("schedule.placed", {
                "pools": ",".join(sorted(
                    e["pool"] for e in intent["slices"].values()))})
            self._count(SCHEDULE_PLACED)
            self.recorder.event(
                nb.obj, "Normal", EVENT_SCHEDULED,
                "Placed %d gang(s) onto pool(s) %s" % (
                    total_gangs,
                    ", ".join(sorted(set(
                        e["pool"] for e in intent["slices"].values())))))
        else:
            self._count(SCHEDULE_NOOP)
        return Result()

    # -- tenancy admission -----------------------------------------------------
    def _pending_eviction(self, key: str) -> bool:
        """True while a write-ahead preemption record in phase Pending
        names this gang as victim: the eviction owns the gang's claims
        until the record retires, and the scheduler must not write (or
        re-write) placement state underneath the teardown."""
        quota = self.api.try_get(
            C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        if quota is None:
            return False
        rec = ((quota.body.get("status", {}) or {})
               .get("preemptions") or {}).get(key)
        return bool(rec) and rec.get("phase") == C.PREEMPTION_PENDING

    def _preempt_fence_holds(self, ann: dict) -> bool:
        """An evicted victim's re-queue fence: it stays parked until the
        beneficiary it was evicted FOR holds the placement — admitting it
        any earlier would hand the freed slices straight back to the
        victim.  The fence lifts when the beneficiary places, stops, or
        vanishes."""
        info = queued_info(ann)
        if info.get("reason") != "preempted":
            return False
        bkey = str(info.get("beneficiary") or "")
        bns, _, bname = bkey.partition("/")
        ben = self.api.try_get("Notebook", bns, bname) if bname else None
        return ben is not None and \
            ben.metadata.deletion_timestamp is None and \
            C.STOP_ANNOTATION not in ben.metadata.annotations and \
            C.ANNOTATION_PLACEMENT not in ben.metadata.annotations

    def _admission(self, nb: Notebook, shape: SliceShape,
                   total_gangs: int, span) -> Optional[Result]:
        """Quota / weighted fair-share admission, BEFORE any claim is
        written.  Returns None to admit, or a queued Result: the gang is
        stamped with the queued annotation (sliceHealth reads "Queued")
        and re-examined on every TenantQuota/pool wakeup plus a
        queue_requeue_s safety net.

        Dequeue order is deterministic and starvation-free: every queued
        gang scores rank + weight * age / queue_aging_s off its
        queued-since stamp, only gangs whose own quota admits them are
        eligible (an over-quota head cannot block the line), and only the
        top-scoring eligible gang admits — ties break on (since,
        namespace, name).  Age grows without bound, so any gang
        eventually outranks any fixed priority class."""
        ann = nb.metadata.annotations or {}
        key = f"{nb.namespace}/{nb.name}"
        # preempt > (re)place: while the write-ahead eviction record is
        # Pending, the engine owns this gang — reconciling its (still
        # present) placement now would race the teardown
        if self._pending_eviction(key):
            span.add_event("schedule.preemption_wait", {})
            return Result(
                requeue_after=max(self.cfg.queue_requeue_s, 1.0))
        if C.ANNOTATION_PLACEMENT in ann:
            return None  # already placed: churn re-reconcile
        # a scheduler that died between claim-write and intent-write must
        # finish its own work, never re-queue behind it
        pool = self.api.try_get(
            C.WARMPOOL_KIND, "",
            pool_object_name(shape.accelerator.name, shape.topology))
        if pool is not None and any(
                e.get("claimedBy") == key
                for e in (pool.body.get("status", {}).get("slices") or {})
                .values()):
            return None
        quota_obj = self.api.try_get(
            C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        now = self.clock.now()
        policy = tenant_policy(quota_obj, nb.namespace)
        priority = resolve_priority(nb, quota_obj)
        if self._preempt_fence_holds(ann):
            return self._queue(nb, span, "preempted", priority, now)
        need = float(shape.chips * total_gangs)
        reader = self.cache if self.cache is not None else self.api
        # notebooks holding pool claims without a placement yet (cold
        # Provisioning reservations mid-flight): their capacity is
        # already spoken for — they count toward quota usage (or a
        # burst of concurrent reservations oversubscribes the quota
        # before any of them lands) and toward the fair-share "another
        # tenant is waiting" signal — but they are NOT in the queued
        # line: they were already admitted
        claimants: set[str] = set()
        for pobj in reader.list(C.WARMPOOL_KIND):
            for e in ((pobj.body.get("status", {}) or {})
                      .get("slices") or {}).values():
                if e.get("claimedBy"):
                    claimants.add(str(e["claimedBy"]))
        waiting_ns: set[str] = set()
        # one reader pass: placed chips per namespace + the queued line
        usage: dict[str, float] = {}
        line: list[dict] = []
        for obj in reader.list("Notebook"):
            if obj.metadata.deletion_timestamp is not None:
                continue
            oann = obj.metadata.annotations or {}
            if C.STOP_ANNOTATION in oann and \
                    C.ANNOTATION_PLACEMENT not in oann:
                continue  # stopped while queued: out of the line
            chips = gang_chips(obj)
            if C.ANNOTATION_PLACEMENT in oann:
                usage[obj.namespace] = \
                    usage.get(obj.namespace, 0.0) + chips
                continue
            if f"{obj.namespace}/{obj.name}" == key:
                continue
            if f"{obj.namespace}/{obj.name}" in claimants:
                usage[obj.namespace] = \
                    usage.get(obj.namespace, 0.0) + chips
                waiting_ns.add(obj.namespace)
                continue
            info = queued_info(oann)
            if not info:
                continue
            opolicy = tenant_policy(quota_obj, obj.namespace)
            op = info.get("priority")
            orank = rank_of(op if op in PRIORITY_RANK
                            else opolicy["priority"])
            since = float(info.get("since", now))
            line.append({
                "ns": obj.namespace, "name": obj.name, "chips": chips,
                "since": since, "quota": opolicy["chip_quota"],
                "score": orank + opolicy["weight"]
                * max(now - since, 0.0)
                / max(self.cfg.queue_aging_s, 1e-9)})
        # 1) hard quota: the tenant's placed chips + this gang must fit
        if policy["chip_quota"] > 0 and \
                usage.get(nb.namespace, 0.0) + need \
                > policy["chip_quota"] + 1e-9:
            return self._queue(nb, span, "quota", priority, now)
        eligible = [e for e in line
                    if e["quota"] <= 0
                    or usage.get(e["ns"], 0.0) + e["chips"]
                    <= e["quota"] + 1e-9]
        capacity = 0.0
        total_w = 0.0
        if line or waiting_ns:
            for node in reader.list("Node"):
                if node.spec.get("unschedulable"):
                    continue
                capacity += parse_quantity(
                    node.body.get("status", {})
                    .get("allocatable", {}).get(C.TPU_RESOURCE, 0))
            active = set(usage) | {e["ns"] for e in line} \
                | waiting_ns | {nb.namespace}
            total_w = sum(tenant_policy(quota_obj, t)["weight"]
                          for t in active)

        def fair_share(tenant: str) -> float:
            w = tenant_policy(quota_obj, tenant)["weight"]
            return capacity * w / total_w if total_w > 0 else capacity

        def over_share(tenant: str, chips: float) -> bool:
            return capacity > 0 and \
                usage.get(tenant, 0.0) + chips \
                > fair_share(tenant) + 1e-9

        i_over = over_share(nb.namespace, need)
        under = [e for e in eligible
                 if not over_share(e["ns"], e["chips"])]
        # 2) deterministic dequeue order: defer to better-scored waiters
        # in my own admission class.  Under my share, only under-share
        # entries count — an over-share head is fair-share-parked by my
        # very presence, and deferring to it would livelock the line.
        # Over my share, the WHOLE eligible line counts: when scarcity is
        # symmetric (every waiter over its share) fair share has nobody
        # to prefer, the aged score alone decides, and the head admitting
        # despite its share is what keeps the line moving at all.
        my_since = float(queued_info(ann).get("since", now))
        my_score = rank_of(priority) + policy["weight"] \
            * max(now - my_since, 0.0) \
            / max(self.cfg.queue_aging_s, 1e-9)
        mine = (-my_score, my_since, nb.namespace, nb.name)
        if any((-e["score"], e["since"], e["ns"], e["name"]) < mine
               for e in (eligible if i_over else under)):
            return self._queue(nb, span, "ordered", priority, now)
        # 3) weighted fair share — binding only while fair share has an
        # actual beneficiary: another tenant's queued gang it would admit
        # right now (under its share), or another tenant's gang already
        # admitted and mid-provision.  Work-conserving: idle capacity is
        # never held back by a share nobody claims, and symmetric
        # over-share scarcity falls through to the dequeue order above.
        if i_over and (
                any(e["ns"] != nb.namespace for e in under)
                or waiting_ns - {nb.namespace}):
            return self._queue(nb, span, "fair-share", priority, now)
        return None

    def _queue(self, nb: Notebook, span, reason: str, priority: str,
               now: float) -> Result:
        """Park the gang: stamp the queued annotation (keeping the
        original since on re-evaluation — aging must accumulate), emit
        the lifecycle event, and requeue on the safety-net interval."""

        def stamp(ann) -> None:
            info = queued_info(ann)
            if info.get("reason") == reason and "since" in info:
                return
            info.setdefault("since", now)
            info["priority"] = priority
            info["reason"] = reason
            ann[C.ANNOTATION_QUEUED] = json.dumps(
                info, sort_keys=True, separators=(",", ":"))

        stamped = _mutate_queue_stamp(self.api, nb.namespace, nb.name,
                                      stamp)
        span.add_event("schedule.queued",
                       {"reason": reason, "priority": priority})
        if stamped:
            self._count(SCHEDULE_QUEUED)
        return Result(requeue_after=max(self.cfg.queue_requeue_s, 1.0))

    # -- reclamation -----------------------------------------------------------
    def _release(self, nb: Notebook, shape: SliceShape, span) -> Result:
        """Culling -> reclamation: once the stopped notebook's slice is
        fully parked (sliceHealth == Stopped — which postdates the
        checkpoint-on-cull handshake by construction), its claims drain
        back into the warm pool (nodes stay provisioned: the capacity is
        resold) and the placement intent is retired so a later restart
        re-places afresh."""
        key = f"{nb.namespace}/{nb.name}"
        # a stopped notebook leaves the admission queue unconditionally —
        # a lingering queued stamp would block the line behind a gang
        # that can never admit
        if C.ANNOTATION_QUEUED in nb.metadata.annotations:
            def drop_queued(ann) -> None:
                ann.pop(C.ANNOTATION_QUEUED, None)

            _mutate_queue_stamp(self.api, nb.namespace, nb.name,
                                drop_queued)
        pool = self.api.try_get(
            C.WARMPOOL_KIND, "", pool_object_name(
                shape.accelerator.name, shape.topology))
        has_claims = pool is not None and any(
            e.get("claimedBy") == key
            for e in (pool.body.get("status", {}).get("slices") or {})
            .values())
        has_intent = C.ANNOTATION_PLACEMENT in nb.metadata.annotations
        if not has_claims and not has_intent:
            return Result()
        health = (nb.status or {}).get("sliceHealth")
        if health != "Stopped":
            # still draining (Stopping) or status not written yet: the
            # notebook controller's status transition re-triggers us
            span.add_event("schedule.release_wait",
                           {"sliceHealth": health or ""})
            return Result()

        def release_claims() -> None:
            # claims MUST drain before the intent annotation goes: the
            # intent is what lets a crashed scheduler re-find its claims,
            # so dropping it first would leak the pool slice forever.
            # Unconditional (no-pool no-ops inside) so the status write
            # dominates drop_intent on every CFG path — enforced by
            # ci/analyzers/write_ahead.py.
            if pool is None:
                return
            live = self.api.get(C.WARMPOOL_KIND, "", pool.name)
            st = copy.deepcopy(live.body.get("status") or {})
            slices = st.setdefault("slices", {})
            changed = False
            for sid in list(slices):
                if slices[sid].get("claimedBy") == key:
                    self._release_entry(slices, sid)
                    changed = True
            if changed:
                live.status = st
                self.api.update_status(live)

        retry_on_conflict(release_claims)

        def drop_intent() -> None:
            live = self.api.get("Notebook", nb.namespace, nb.name)
            if C.ANNOTATION_PLACEMENT in live.metadata.annotations:
                del live.metadata.annotations[C.ANNOTATION_PLACEMENT]
                self.api.update(live)

        retry_on_conflict(drop_intent)
        span.add_event("schedule.released")
        self._count(SCHEDULE_RELEASED)
        self.recorder.event(
            nb.obj, "Normal", EVENT_RELEASED,
            "Slice capacity returned to the warm pool")
        return Result()

    @staticmethod
    def _release_entry(slices: dict, sid: str) -> None:
        """Un-claim one pool slice: external (bypass) entries vanish —
        the capacity was never pool-managed; warm entries turn Ready
        (Provisioning reservations stay Provisioning) and rejoin the
        claimable pool with their nodes intact."""
        e = slices[sid]
        if e.get("external"):
            del slices[sid]
            return
        if e.get("state") == C.WARMSLICE_CLAIMED:
            e["state"] = C.WARMSLICE_READY
        e.pop("claimedBy", None)
        e.pop("claimedSlice", None)

    # -- capacity inventory ----------------------------------------------------
    def _inventory(self, shape: SliceShape,
                   pool_status: dict) -> list[NodeCapacity]:
        """Schedulable capacity for bypass placement: nodes matching the
        shape's accelerator/topology labels, grouped by node pool, with
        free chips net of bound pods AND standing reservations — every
        claimed pool entry whose pods have not bound yet, INCLUDING the
        claiming notebook's own entries: during one _place pass over a
        multi-slice gang, slice N must see slice N-1's assignment as
        taken or the gang double-books the same nodes.  Nodes owned by
        any warm pool are excluded — warm capacity moves only through
        claims."""
        reader = self.cache if self.cache is not None else self.api
        warm_pools: set[str] = set()
        reservations: dict[str, float] = {}
        # pods once: per-node bound chips, per (node, notebook) bound chips
        bound: dict[str, float] = {}
        bound_by_nb: dict[tuple[str, str], float] = {}
        for pod in reader.list("Pod"):
            node = pod.spec.get("nodeName")
            if not node:
                continue
            chips = _tpu_request(pod.spec)
            if chips <= 0:
                continue
            bound[node] = bound.get(node, 0.0) + chips
            owner = "%s/%s" % (
                pod.namespace,
                pod.metadata.labels.get(C.NOTEBOOK_NAME_LABEL, ""))
            bound_by_nb[(node, owner)] = \
                bound_by_nb.get((node, owner), 0.0) + chips
        for pool_obj in self.api.list(C.WARMPOOL_KIND):
            spec = pool_obj.spec
            try:
                pshape = resolve(spec.get("accelerator", ""),
                                 spec.get("topology", ""))
            except TopologyError:
                continue
            entries = (pool_obj.body.get("status", {})
                       .get("slices") or {})
            if pool_obj.name == pool_object_name(
                    shape.accelerator.name, shape.topology):
                entries = pool_status.get("slices") or {}
            for e in entries.values():
                if not e.get("external"):
                    warm_pools.add(e.get("pool", ""))
                claimant = e.get("claimedBy", "")
                for node in e.get("nodes") or []:
                    already = bound_by_nb.get((node, claimant), 0.0) \
                        if claimant else 0.0
                    reservations[node] = reservations.get(node, 0.0) + \
                        max(pshape.chips_per_host - already, 0.0)
        out: list[NodeCapacity] = []
        for node in reader.list("Node"):
            if node.spec.get("unschedulable"):
                continue
            labels = node.metadata.labels
            if labels.get(C.GKE_TPU_ACCELERATOR_LABEL) != \
                    shape.accelerator.gke_label:
                continue
            if labels.get(C.GKE_TPU_TOPOLOGY_LABEL) != shape.topology:
                continue
            pool = labels.get(C.GKE_NODEPOOL_LABEL) or node.name
            if pool in warm_pools:
                continue
            total = parse_quantity(
                node.body.get("status", {})
                .get("allocatable", {}).get(C.TPU_RESOURCE, 0))
            free = total - bound.get(node.name, 0.0) \
                - reservations.get(node.name, 0.0)
            out.append(NodeCapacity(node.name, pool, free, total))
        return out

    # -- plumbing --------------------------------------------------------------
    def _ensure_pool(self, shape: SliceShape) -> KubeObject:
        name = pool_object_name(shape.accelerator.name, shape.topology)
        obj = self.api.try_get(C.WARMPOOL_KIND, "", name)
        if obj is not None:
            return obj
        try:
            return self.api.create(new_pool_object(
                shape.accelerator.name, shape.topology))
        except AlreadyExistsError:
            return self.api.get(C.WARMPOOL_KIND, "", name)

    def _count(self, result: str) -> None:
        self.metrics.schedule_attempts.labels(result).inc()


def _tpu_request(pod_spec: dict) -> float:
    total = 0.0
    for c in pod_spec.get("containers", []):
        req = (c.get("resources", {}).get("requests") or {}) \
            .get(C.TPU_RESOURCE)
        if req is not None:
            total += parse_quantity(req)
    return total


def new_pool_object(accelerator: str, topology: str) -> KubeObject:
    return KubeObject(
        api_version="kubeflow.org/v1",
        kind=C.WARMPOOL_KIND,
        metadata=ObjectMeta(name=pool_object_name(accelerator, topology)),
        body={"spec": {"accelerator": accelerator, "topology": topology}},
    )


# -- warm-pool controller ------------------------------------------------------
class WarmPoolController:
    """Reconciles TPUWarmPool objects: turns Provisioning reservations
    into Ready slices once their readyAt deadline passes (via the
    pluggable SliceProvisioner — FakeCluster.provision_slice in
    standalone mode, the cloud's node auto-provisioner in real life),
    garbage-collects claims whose notebook vanished, and runs the
    hit-rate autoscaler + sizing loop for shapes listed in
    WARMPOOL_SHAPES."""

    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        metrics: NotebookMetrics,
        provisioner=None,
        clock: Optional[Clock] = None,
    ):
        self.api = api
        self.cfg = cfg
        self.metrics = metrics
        self.provisioner = provisioner
        self.clock = clock or Clock()
        self._managed_shapes = {
            (a, t) for a, t in parse_warmpool_shapes(cfg.warmpool_shapes)}

    def reconcile(self, req: Request) -> Result:
        obj = self.api.try_get(C.WARMPOOL_KIND, req.namespace, req.name)
        if obj is None:
            return Result()
        try:
            shape = resolve(obj.spec.get("accelerator", ""),
                            obj.spec.get("topology", ""))
        except TopologyError:
            return Result()
        requeue = [0.0]

        def attempt() -> None:
            live = self.api.get(C.WARMPOOL_KIND, req.namespace, req.name)
            before = copy.deepcopy(live.body.get("status") or {})
            st = copy.deepcopy(before)
            requeue[0] = self._step(st, shape)
            if st != before:
                live.status = st
                self.api.update_status(live)

        retry_on_conflict(attempt)
        if requeue[0] > 0:
            return Result(requeue_after=requeue[0])
        return Result()

    def _step(self, st: dict, shape: SliceShape) -> float:
        now = self.clock.now()
        st.setdefault("target", self.cfg.warmpool_size)
        st.setdefault("seq", 0)
        for k in ("hits", "misses", "bypass"):
            st.setdefault(k, 0)
        slices = st.setdefault("slices", {})

        # orphan-claim GC: a deleted notebook never released — reclaim
        # (the failover-safe twin of the scheduler's Stopped release)
        for sid in list(slices):
            claimant = slices[sid].get("claimedBy")
            if not claimant:
                continue
            ns, _, name = claimant.partition("/")
            nb = self.api.try_get("Notebook", ns, name)
            if nb is None or nb.metadata.deletion_timestamp is not None:
                SliceScheduler._release_entry(slices, sid)

        # Provisioning -> Ready once the deadline passes; the provisioner
        # call is idempotent, so an RMW conflict retry re-runs it safely
        next_due: Optional[float] = None
        for sid in sorted(slices):
            e = slices[sid]
            if e.get("state") != C.WARMSLICE_PROVISIONING:
                continue
            ready_at = float(e.get("readyAt", 0.0))
            if ready_at <= now:
                e["nodes"] = self._provision(shape, e["pool"])
                e["state"] = C.WARMSLICE_READY
                e.pop("readyAt", None)
            elif next_due is None or ready_at < next_due:
                next_due = ready_at

        if (shape.accelerator.name, shape.topology) in self._managed_shapes \
                and self.cfg.warmpool_size > 0:
            next_due = self._autoscale(st, shape, now, next_due)
        else:
            # unmanaged shape (not in WARMPOOL_SHAPES): idle capacity is
            # not kept warm — a released slice is torn straight back down,
            # which is exactly the cold path the warm pool exists to beat
            st["target"] = 0
            for sid in sorted(slices):
                e = slices[sid]
                if e.get("state") == C.WARMSLICE_READY \
                        and not e.get("claimedBy") \
                        and not e.get("external"):
                    self._deprovision(e["pool"])
                    del slices[sid]

        return max(next_due - now, 0.0) if next_due is not None else 0.0

    def _autoscale(self, st: dict, shape: SliceShape, now: float,
                   next_due: Optional[float]) -> Optional[float]:
        """Grow the target by the misses observed since the last pass
        (every miss is a notebook that paid the cold path — the pool was
        too small); decay it one step back toward the configured base
        while the cumulative hit rate holds above the goal and idle
        capacity exceeds the target.  Then size the pool to the target:
        provision the shortfall, retire idle excess (highest id first —
        the youngest slices go back first, deterministically)."""
        slices = st["slices"]
        base = self.cfg.warmpool_size
        target = int(st.get("target", base))
        dm = st["misses"] - st.get("lastMisses", 0)
        dh = st["hits"] - st.get("lastHits", 0)
        # windowed hit rate (since the last pass): the cumulative rate
        # never recovers from an early burst of misses, so decay would
        # stall forever on it.  An empty window counts as healthy.
        window = dh + dm
        hit_rate = (dh / window) if window else 1.0
        unclaimed = [
            sid for sid in sorted(slices)
            if not slices[sid].get("claimedBy")
            and not slices[sid].get("external")]
        idle_ready = [
            sid for sid in unclaimed
            if slices[sid].get("state") == C.WARMSLICE_READY]
        last_decay = float(st.setdefault("lastDecayAt", now))
        if dm > 0:
            # every miss is a notebook that paid the cold path: grow, and
            # reset the scale-down cooldown
            target = min(target + dm, self.cfg.warmpool_max_size)
            st["lastDecayAt"] = now
        elif target > base and len(idle_ready) >= target \
                and hit_rate >= self.cfg.warmpool_target_hit_rate \
                and now - last_decay >= self.cfg.warmpool_decay_s:
            # a full cooldown with zero misses and the pool fully idle:
            # one step back toward the configured base
            target -= 1
            st["lastDecayAt"] = now
        st["lastMisses"] = st["misses"]
        st["lastHits"] = st["hits"]
        st["target"] = target
        if target > base:
            # arm the next decay check — an idle pool gets no events, so
            # the cooldown must wake the reconciler itself
            decay_due = float(st["lastDecayAt"]) + self.cfg.warmpool_decay_s
            if next_due is None or decay_due < next_due:
                next_due = decay_due

        while len(unclaimed) < target:
            st["seq"] += 1
            sid = f"ws-{st['seq']:04d}"
            ready_at = now + self.cfg.warmpool_provision_s
            slices[sid] = {
                "state": C.WARMSLICE_PROVISIONING,
                "pool": "warm-%s-%s-%04d" % (
                    shape.accelerator.name, shape.topology, st["seq"]),
                "readyAt": ready_at,
            }
            unclaimed.append(sid)
            if next_due is None or ready_at < next_due:
                next_due = ready_at
        # shrink: cancel not-yet-up Provisioning entries first (nothing to
        # tear down), then retire the youngest idle Ready slices — a just-
        # reclaimed slice must never lose out to a pending turn-up
        cancellable = [
            sid for sid in unclaimed
            if slices[sid].get("state") == C.WARMSLICE_PROVISIONING]
        while len(unclaimed) > target and (cancellable or idle_ready):
            sid = cancellable.pop() if cancellable else idle_ready.pop()
            self._deprovision(slices[sid]["pool"])
            del slices[sid]
            unclaimed.remove(sid)
        return next_due

    def _provision(self, shape: SliceShape, pool: str) -> list[str]:
        if self.provisioner is None:
            # real-cluster mode: capacity turn-up belongs to the cloud's
            # node auto-provisioner; the pool entry still tracks intent
            return []
        return list(self.provisioner.provision_slice(shape, pool))

    def _deprovision(self, pool: str) -> None:
        if self.provisioner is not None:
            self.provisioner.deprovision_slice(pool)


# -- wiring --------------------------------------------------------------------
def setup_scheduler(
    mgr: Manager,
    cfg: CoreConfig,
    metrics: NotebookMetrics,
    provisioner=None,
    policy: Optional[PlacementPolicy] = None,
    session=None,
) -> tuple[SliceScheduler, WarmPoolController]:
    """Register the SliceScheduler + WarmPoolController pair (plus the
    PreemptionEngine and its TenantQuota reconciler) and seed the
    per-shape pool objects for WARMPOOL_SHAPES.  `provisioner` is the
    data-plane hook (FakeCluster in standalone mode) that actually turns
    capacity up/down; None means capacity management is external.
    `session` is the session-state store checkpoint-then-preempt secures
    victim state through (the engine opens one from
    CHECKPOINT_STORE_URI when not passed)."""
    api = mgr.api
    sched = SliceScheduler(
        api, cfg, metrics, EventRecorder(api, "slice-scheduler"),
        clock=mgr.clock, cache=mgr.cache, policy=policy)
    pools = WarmPoolController(
        api, cfg, metrics, provisioner=provisioner, clock=mgr.clock)
    # deferred import: preemption.py imports this module at top level
    from .preemption import PreemptionEngine

    engine = PreemptionEngine(
        api, cfg, metrics, EventRecorder(api, "preemption"),
        clock=mgr.clock, cache=mgr.cache, session=session)
    sched.preemption = engine
    # exposed for tests and the chaos soak's fault injection
    mgr.preemption_engine = engine

    def pool_to_notebooks(obj: KubeObject) -> list[Request]:
        # a pool transition (reservation turned Ready, slice released)
        # re-evaluates exactly the notebooks holding entries in it
        out: list[Request] = []
        seen: set[str] = set()
        for e in (obj.body.get("status", {}).get("slices") or {}).values():
            claimant = e.get("claimedBy")
            if claimant and claimant not in seen:
                seen.add(claimant)
                ns, _, name = claimant.partition("/")
                out.append(Request(ns, name))
        return out

    def notebook_to_pool(obj: KubeObject) -> list[Request]:
        tpu = obj.spec.get("tpu") or {}
        accel = str(tpu.get("accelerator", ""))
        topo = str(tpu.get("topology", ""))
        if not accel or not topo:
            return []
        return [Request("", pool_object_name(accel, topo))]

    def quota_to_notebooks(obj: KubeObject) -> list[Request]:
        # a tenancy-policy change or a preemption-record transition
        # re-evaluates every queued gang plus both record parties — this
        # is what wakes the queue the moment quota frees up or an
        # eviction completes
        out: list[Request] = []
        seen: set[str] = set()

        def add(ns: str, name: str) -> None:
            k = f"{ns}/{name}"
            if name and k not in seen:
                seen.add(k)
                out.append(Request(ns, name))

        for o in api.list("Notebook"):
            if C.ANNOTATION_QUEUED in o.metadata.annotations:
                add(o.namespace, o.name)
        st = obj.body.get("status", {}) or {}
        for rec in (st.get("preemptions") or {}).values():
            for k in (rec.get("beneficiary", ""), rec.get("victim", "")):
                ns, _, name = k.partition("/")
                add(ns, name)
        return out

    mgr.register(
        "slice-scheduler",
        sched,
        for_kind="Notebook",
        # no suppress_status_only here: release keys off the Stopped
        # sliceHealth transition, which IS a status-only write
        watches=[
            WatchSpec(kind=C.WARMPOOL_KIND, mapper=pool_to_notebooks),
            WatchSpec(kind=C.TENANTQUOTA_KIND, mapper=quota_to_notebooks),
        ],
    )
    mgr.register(
        "preemption",
        engine,
        for_kind=C.TENANTQUOTA_KIND,
    )
    mgr.register(
        "warm-pool",
        pools,
        for_kind=C.WARMPOOL_KIND,
        watches=[WatchSpec(
            kind="Notebook",
            mapper=notebook_to_pool,
            # only deletions matter: orphan-claim GC
            predicate=lambda ev: ev.type is EventType.DELETED,
        )],
    )
    for accel, topo in parse_warmpool_shapes(cfg.warmpool_shapes):
        if api.try_get(C.WARMPOOL_KIND, "",
                       pool_object_name(accel, topo)) is None:
            try:
                api.create(new_pool_object(accel, topo))
            except AlreadyExistsError:
                pass
    return sched, pools
