"""Speculative decoding: EXACTNESS is the whole contract.

Greedy speculative output must be token-identical to the target's own
greedy decode — with a perfect draft (the target itself), with a
different tiny draft, and across batch rows (min-acceptance semantics).
The steps counter pins the speed mechanics: a perfect draft finishes in
~N/gamma rounds, a garbage draft degrades toward one token per round but
never changes the tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.generate import generate
from kubeflow_tpu.models.speculative import speculative_generate
from kubeflow_tpu.models.transformer import Transformer


def _params(cfg, seed=0):
    return Transformer(cfg).init(jax.random.PRNGKey(seed),
                                 jnp.ones((1, 8), jnp.int32))["params"]


class TestSpeculative:
    def _check_exact(self, draft_cfg, draft_params, gamma, n_new=12):
        cfg = TINY
        params = _params(cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                    cfg.vocab_size)
        ref = generate(cfg, params, prompt, max_new_tokens=n_new)
        out, steps = speculative_generate(
            cfg, params, draft_cfg, draft_params, prompt, n_new,
            gamma=gamma)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        return int(steps)

    def test_perfect_draft_is_exact_and_fast(self):
        """Draft == target: full acceptance every round -> ~N/(gamma-1+1)
        rounds (acceptance caps at gamma-1, +1 correction token)."""
        cfg = TINY
        params = _params(cfg)
        steps = self._check_exact(cfg, params, gamma=4, n_new=12)
        # 12 tokens, gamma-1=3 accepted + 1 correction per round = 4/round
        # (first token comes from prefill) -> ceil(11/4) = 3 rounds
        assert steps <= 4, steps

    def test_mismatched_draft_is_still_exact(self):
        """A differently-initialized draft (garbage agreement) must not
        change a single output token."""
        draft_cfg = TINY.with_(num_layers=1, embed_dim=32, num_heads=2,
                               num_kv_heads=1, head_dim=16, mlp_dim=64)
        draft_params = _params(draft_cfg, seed=7)
        steps = self._check_exact(draft_cfg, draft_params, gamma=4,
                                  n_new=12)
        # garbage draft: close to one token per round, never more than N
        assert steps <= 12, steps

    def test_gamma_guard(self):
        cfg = TINY
        params = _params(cfg)
        prompt = jnp.ones((1, 4), jnp.int32)
        try:
            speculative_generate(cfg, params, cfg, params, prompt, 4,
                                 gamma=1)
        except ValueError as e:
            assert "gamma" in str(e)
        else:
            raise AssertionError("gamma=1 should be rejected")
