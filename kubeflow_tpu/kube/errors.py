"""API error model mirroring apimachinery's StatusError reasons.

The reference leans on k8s error predicates (apierrs.IsNotFound,
retry.RetryOnConflict) throughout, e.g.
components/notebook-controller/controllers/culling_controller.go:107,125,144.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar


class ApiError(Exception):
    reason = "Unknown"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFoundError(ApiError):
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    reason = "AlreadyExists"


class ConflictError(ApiError):
    reason = "Conflict"


class InvalidError(ApiError):
    reason = "Invalid"


class ForbiddenError(ApiError):
    reason = "Forbidden"


class GoneError(ApiError):
    """HTTP 410: requested watch resourceVersion fell out of the history
    window — the client must relist (client-go reflector does the same)."""

    reason = "Expired"


class ServerError(ApiError):
    """Transport/5xx failure talking to a real apiserver."""

    reason = "InternalError"


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)


def is_conflict(err: Exception) -> bool:
    return isinstance(err, ConflictError)


def is_already_exists(err: Exception) -> bool:
    return isinstance(err, AlreadyExistsError)


T = TypeVar("T")


def retry_on_conflict(
    fn: Callable[[], T],
    steps: int = 5,
    initial_backoff_s: float = 0.01,
    factor: float = 2.0,
    max_backoff_s: float = 0.25,
    jitter: float = 0.1,
    sleep_fn: Optional[Callable[[float], None]] = None,
) -> T:
    """Equivalent of retry.RetryOnConflict(retry.DefaultRetry, fn), with
    client-go's wait.Backoff semantics: capped exponential backoff plus
    jitter between attempts, so a conflict storm (optimistic-concurrency
    herd, injected 409s from a chaos plan) spreads out instead of
    hot-looping.  Steps mirror DefaultRetry (5 attempts); the cap keeps the
    worst case bounded (~0.6s total at the defaults).  `sleep_fn` is
    injectable for deterministic tests (defaults to time.sleep)."""
    backoff = initial_backoff_s
    sleep = sleep_fn if sleep_fn is not None else time.sleep
    last: Exception | None = None
    for attempt in range(steps):
        try:
            return fn()
        except ConflictError as err:
            last = err
            if backoff > 0 and attempt < steps - 1:
                delay = min(backoff, max_backoff_s)
                if jitter > 0:
                    delay *= 1.0 + jitter * random.random()
                sleep(delay)
                backoff *= factor
    assert last is not None
    raise last
