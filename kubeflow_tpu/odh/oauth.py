"""Legacy OAuthClient migration cleanup.

Port of notebook_oauth.go: RHOAI 2.x created one cluster-scoped OAuthClient
per notebook; on notebook deletion the matching client (named
`{name}-{namespace}-oauth-client`) is deleted via finalizer
(notebook_oauth.go:67-96).
"""

from __future__ import annotations

from ..api.types import Notebook
from ..kube import ApiServer, NotFoundError


def oauth_client_name(nb: Notebook) -> str:
    return f"{nb.name}-{nb.namespace}-oauth-client"


def delete_oauth_client(api: ApiServer, nb: Notebook) -> None:
    """deleteOAuthClient (notebook_oauth.go:67-96); absence is success."""
    try:
        api.delete("OAuthClient", "", oauth_client_name(nb))
    except NotFoundError:
        pass
