"""Cluster TLS security-profile negotiation + change watcher.

Port of the ODH manager's TLS posture handling (odh main.go:68-78,178-214,
324-340 and its tls package): read the OpenShift `APIServer` cluster CR's
`spec.tlsSecurityProfile`, translate it to a cipher list + minimum TLS
version for the webhook/metrics servers, fall back to the hardened Mozilla
Intermediate set when the CR doesn't exist (non-OpenShift), and watch for
profile changes — a change triggers a deliberate graceful restart so the
servers reload with the new posture.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..kube import ApiServer, Manager, Request, Result

# Mozilla Intermediate (odh main.go:70-78) — the hardened fallback
INTERMEDIATE_CIPHERS = (
    "TLS_ECDHE_ECDSA_WITH_AES_128_GCM_SHA256",
    "TLS_ECDHE_RSA_WITH_AES_128_GCM_SHA256",
    "TLS_ECDHE_ECDSA_WITH_AES_256_GCM_SHA384",
    "TLS_ECDHE_RSA_WITH_AES_256_GCM_SHA384",
    "TLS_ECDHE_ECDSA_WITH_CHACHA20_POLY1305_SHA256",
    "TLS_ECDHE_RSA_WITH_CHACHA20_POLY1305_SHA256",
)

# OpenShift named profiles (configv1.TLSProfiles subset we honor)
_PROFILES: dict[str, tuple[str, tuple[str, ...]]] = {
    "Old": ("VersionTLS10", INTERMEDIATE_CIPHERS),
    "Intermediate": ("VersionTLS12", INTERMEDIATE_CIPHERS),
    "Modern": (
        "VersionTLS13",
        (
            "TLS_AES_128_GCM_SHA256",
            "TLS_AES_256_GCM_SHA384",
            "TLS_CHACHA20_POLY1305_SHA256",
        ),
    ),
}


@dataclass(frozen=True)
class TLSProfileSpec:
    min_version: str
    ciphers: tuple[str, ...]
    source: str  # "apiserver" | "fallback"


HARDENED_FALLBACK = TLSProfileSpec(
    "VersionTLS12", INTERMEDIATE_CIPHERS, "fallback"
)


def profile_from_spec(spec: dict) -> TLSProfileSpec:
    """tlsSecurityProfile dict -> resolved profile.  `Custom` profiles carry
    explicit ciphers/minTLSVersion; named profiles use the table."""
    profile_type = spec.get("type", "Intermediate")
    if profile_type == "Custom":
        custom = spec.get("custom") or {}
        return TLSProfileSpec(
            custom.get("minTLSVersion", "VersionTLS12"),
            tuple(custom.get("ciphers") or INTERMEDIATE_CIPHERS),
            "apiserver",
        )
    min_version, ciphers = _PROFILES.get(profile_type, _PROFILES["Intermediate"])
    return TLSProfileSpec(min_version, ciphers, "apiserver")


def fetch_apiserver_tls_profile(api: ApiServer) -> TLSProfileSpec:
    """FetchAPIServerTLSProfile analog: APIServer CR `cluster` (cluster
    scoped), hardened fallback when absent (odh main.go:191-201)."""
    apiserver = api.try_get("APIServer", "", "cluster")
    if apiserver is None:
        return HARDENED_FALLBACK
    spec = apiserver.spec.get("tlsSecurityProfile") or {}
    if not spec:
        return HARDENED_FALLBACK
    return profile_from_spec(spec)


@dataclass
class SecurityProfileWatcher:
    """Reconciler on the APIServer CR: when the resolved profile differs
    from the one the servers started with, invoke on_change (the manager
    cancels/restarts — odh main.go:324-340)."""

    api: ApiServer
    initial: TLSProfileSpec
    on_change: Callable[[TLSProfileSpec, TLSProfileSpec], None]
    _fired: bool = field(default=False, init=False)

    def reconcile(self, req: Request) -> Result:
        if req.name != "cluster" or self._fired:
            return Result()
        current = fetch_apiserver_tls_profile(self.api)
        if current.source == "apiserver" and current != self.initial:
            self._fired = True
            self.on_change(self.initial, current)
        return Result()

    def setup(self, mgr: Manager) -> None:
        mgr.register("tls-profile-watcher", self, for_kind="APIServer")
