"""Fleet SLO engine, continuous profiler, fleet rollup, diagnostics
bundle (ISSUE 10).

Covers, under FakeClock where timing matters:
  - burn-rate math over sliding windows (latency bucket snapping, ratio
    objectives, window anchoring);
  - alert lifecycle: fire -> persist across scrapes -> resolve on
    recovery -> re-fire as a NEW alert, with exemplar trace ids latched
    from the attempt stream and a bounded history;
  - FlightRecorder.overlapping_attempts() sweep == brute force on seeded
    histories (including the long-attempt-spans-many shape the old
    adjacent-pair check missed);
  - /debug/fleet rollup counts == apiserver ground truth, via the
    cache's incremental census;
  - profiler: off by default in the wired stack, deterministic
    sample_once attribution via the live span-stack mirror, bounded
    stack store, self-overhead measurement;
  - ops.diagnose: in-process and HTTP bundles from which the slowest
    attempt is fully reconstructable offline, with redacted config.
"""

import json
import random
import threading
import urllib.request
from types import SimpleNamespace

import pytest

from kubeflow_tpu.api.types import CONDITION_RECOVERY_EXHAUSTED, Notebook, \
    TPUSpec
from kubeflow_tpu.core.metrics import FLEET_STATES, NotebookMetrics, \
    fleet_state
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.ops.diagnose import REDACTED, collect_http, collect_local
from kubeflow_tpu.ops.diagnose import main as diagnose_main
from kubeflow_tpu.ops.diagnose import redacted_config
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig
from kubeflow_tpu.utils.flightrecorder import FlightRecorder
from kubeflow_tpu.utils.metrics import Registry
from kubeflow_tpu.utils.profiler import UNATTRIBUTED, ContinuousProfiler, \
    attribute
from kubeflow_tpu.utils.slo import KIND_LATENCY, KIND_RATIO, Objective, \
    SLOEngine, default_objectives, window_label


def _engine(clock, reg, objectives, threshold=2.0, windows=(300.0, 3600.0),
            **kw):
    return SLOEngine(objectives, [reg], clock, windows=windows,
                     burn_threshold=threshold, **kw)


ERROR_OBJ = Objective(
    "errors", KIND_RATIO, "controller_runtime_reconcile_total",
    target_ratio=0.99, label="result", bad_values=("error",))


class TestBurnRateMath:
    def setup_method(self):
        self.clock = FakeClock()
        self.reg = Registry()
        self.total = self.reg.counter(
            "controller_runtime_reconcile_total", "t",
            labels=("controller", "result"))

    def test_clean_traffic_burns_nothing(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        for _ in range(12):
            self.total.labels("notebook", "success").inc(100)
            self.clock.advance(300)
            eng.evaluate()
        stats = eng.evaluate()["errors"]
        assert stats["burn_rates"] == {"5m": 0.0, "1h": 0.0}
        assert stats["budget_remaining_ratio"] == 1.0
        assert not eng.firing()

    def test_burst_burn_rates_exact(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        # one clean hour, then a 50%-errors minute: the short window sees
        # 50% bad / 1% budget = burn 50, the long window dilutes
        for _ in range(12):
            self.total.labels("notebook", "success").inc(100)
            self.clock.advance(300)
            eng.evaluate()
        self.total.labels("notebook", "success").inc(50)
        self.total.labels("notebook", "error").inc(50)
        self.clock.advance(60)
        stats = eng.evaluate()["errors"]
        # short window: the last clean round's 100 successes are still
        # inside it, so 50 bad of 200 events / 1% budget = burn 25
        assert stats["burn_rates"]["5m"] == pytest.approx(25.0)
        # long window: 50 bad of (1100 good + 50 bad + 50) events since
        # the 1h anchor; just assert it is diluted but nonzero
        assert 0 < stats["burn_rates"]["1h"] < stats["burn_rates"]["5m"]
        assert stats["budget_remaining_ratio"] < 0.0  # budget overspent

    def test_window_anchor_forgets_old_errors(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        self.total.labels("notebook", "error").inc(100)
        self.clock.advance(60)
        assert eng.evaluate()["errors"]["burn_rates"]["5m"] > 0
        # two clean hours later both windows have forgotten the burst
        for _ in range(24):
            self.total.labels("notebook", "success").inc(10)
            self.clock.advance(300)
            eng.evaluate()
        stats = eng.evaluate()["errors"]
        assert stats["burn_rates"] == {"5m": 0.0, "1h": 0.0}
        assert stats["budget_remaining_ratio"] == 1.0

    def test_latency_threshold_snaps_to_bucket(self):
        hist = self.reg.histogram("lat_seconds", "l", labels=("c",),
                                  buckets=(0.1, 1.0, 10.0))
        # threshold 0.5 snaps UP to the 1.0 bucket bound: a 0.9s
        # observation still counts good (the exposition cannot tell 0.5
        # from 1.0 apart), a 5s one is bad
        obj = Objective("lat", KIND_LATENCY, "lat_seconds", threshold_s=0.5)
        eng = _engine(self.clock, self.reg, (obj,))
        hist.labels("a").observe(0.9)
        hist.labels("a").observe(5.0)
        hist.labels("b").observe(0.05)
        self.clock.advance(10)
        stats = eng.evaluate()["lat"]
        # 1 bad of 3 -> 33.3% / 1% budget
        assert stats["burn_rates"]["5m"] == pytest.approx((1 / 3) / 0.01)

    def test_latency_threshold_above_all_buckets_counts_all_good(self):
        hist = self.reg.histogram("lat_seconds", "l", buckets=(0.1, 1.0))
        obj = Objective("lat", KIND_LATENCY, "lat_seconds", threshold_s=99.0)
        eng = _engine(self.clock, self.reg, (obj,))
        hist.observe(50.0)
        self.clock.advance(10)
        assert eng.evaluate()["lat"]["burn_rates"]["5m"] == 0.0

    def test_ratio_total_values_restrict_denominator(self):
        hits = self.reg.counter("notebook_warmpool_hits_total", "h",
                                labels=("result",))
        obj = Objective("hit_rate", KIND_RATIO,
                        "notebook_warmpool_hits_total", target_ratio=0.6,
                        label="result", bad_values=("miss",),
                        total_values=("hit", "miss"))
        eng = _engine(self.clock, self.reg, (obj,))
        hits.labels("hit").inc(3)
        hits.labels("miss").inc(1)
        hits.labels("bypass").inc(100)  # neutral: not pool traffic
        self.clock.advance(10)
        stats = eng.evaluate()["hit_rate"]
        # 25% misses against a 40% budget: burning but within budget
        assert stats["burn_rates"]["5m"] == pytest.approx(0.25 / 0.4)
        assert stats["budget_remaining_ratio"] > 0.0

    def test_unregistered_metric_is_quietly_empty(self):
        obj = Objective("ghost", KIND_LATENCY, "no_such_family_seconds",
                        threshold_s=1.0)
        eng = _engine(self.clock, self.reg, (obj,))
        stats = eng.evaluate()["ghost"]
        assert stats["events_long_window"] == 0
        assert stats["budget_remaining_ratio"] == 1.0

    def test_gauges_exported(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        self.total.labels("notebook", "error").inc(10)
        self.clock.advance(30)
        eng.evaluate()
        text = self.reg.render()
        assert 'notebook_slo_burn_rate{objective="errors",window="5m"}' \
            in text
        assert 'notebook_slo_error_budget_remaining_ratio{' \
            'objective="errors"}' in text
        assert 'notebook_slo_alert_firing{objective="errors"}' in text

    def test_window_label(self):
        assert window_label(300) == "5m"
        assert window_label(3600) == "1h"
        assert window_label(7200) == "2h"
        assert window_label(90) == "90s"

    def test_default_objectives_follow_config(self):
        cfg = CoreConfig()
        names = {o.name for o in default_objectives(cfg)}
        assert names == {"time_to_ready", "event_to_reconcile",
                         "reconcile_errors", "recovery_duration",
                         "promotion_duration", "tenant_fairness"}
        cfg = CoreConfig(enable_slice_scheduler=True)
        assert "warmpool_hit_rate" in \
            {o.name for o in default_objectives(cfg)}
        cfg = CoreConfig(slo_reconcile_error_rate=0.0)
        assert "reconcile_errors" not in \
            {o.name for o in default_objectives(cfg)}
        cfg = CoreConfig(slo_promotion_p99_s=0.0)
        assert "promotion_duration" not in \
            {o.name for o in default_objectives(cfg)}
        cfg = CoreConfig(slo_tenant_fairness=0.0)
        assert "tenant_fairness" not in \
            {o.name for o in default_objectives(cfg)}


class TestAlertLifecycle:
    def setup_method(self):
        self.clock = FakeClock()
        self.reg = Registry()
        self.total = self.reg.counter(
            "controller_runtime_reconcile_total", "t",
            labels=("controller", "result"))

    def _burst(self, errors=50, good=50):
        self.total.labels("notebook", "success").inc(good)
        self.total.labels("notebook", "error").inc(errors)

    def _recover(self, eng, rounds=3):
        for _ in range(rounds):
            self.total.labels("notebook", "success").inc(200)
            self.clock.advance(150)
            eng.evaluate()

    def test_fire_persist_resolve_refire(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        self._burst()
        self.clock.advance(30)
        eng.evaluate()
        firing = eng.firing()
        assert [a.objective for a in firing] == ["errors"]
        first = firing[0]
        assert first.state == "firing" and first.fired_at == self.clock.now()
        assert first.burn_rates["5m"] >= 2.0

        # persists (deduped) across scrapes while the breach continues
        self.clock.advance(30)
        eng.evaluate()
        assert eng.firing()[0].seq == first.seq
        assert len(eng.alert_history()) == 1

        # resolves once the short window recovers
        self._recover(eng)
        assert not eng.firing()
        hist = eng.alert_history()
        assert len(hist) == 1 and hist[0].state == "resolved"
        assert hist[0].resolved_at > hist[0].fired_at

        # a fresh breach after resolution fires a NEW alert
        self._burst(errors=200, good=0)
        self.clock.advance(30)
        eng.evaluate()
        assert eng.firing()[0].seq == first.seq + 1
        assert len(eng.alert_history()) == 2

    def test_short_blip_against_calm_long_window_does_not_fire(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,), threshold=5.0)
        # a big clean hour, then a tiny error blip: short window burns
        # above threshold, long window stays calm -> no page
        for _ in range(12):
            self.total.labels("notebook", "success").inc(10_000)
            self.clock.advance(300)
            eng.evaluate()
        self.clock.advance(300)  # idle: the clean bulk leaves the short
        eng.evaluate()           # window but stays in the long one
        self._burst(errors=10, good=90)
        self.clock.advance(30)
        stats = eng.evaluate()["errors"]
        assert stats["burn_rates"]["5m"] >= 5.0
        assert stats["burn_rates"]["1h"] < 5.0
        assert not eng.firing()

    def test_alert_latches_errored_attempt_trace(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        eng.observe_attempt(SimpleNamespace(
            result="error", error="Boom: x", duration_s=0.1,
            trace_id="deadbeef" * 4))
        self._burst()
        self.clock.advance(30)
        eng.evaluate()
        assert eng.firing()[0].trace_id == "deadbeef" * 4

    def test_latency_alert_prefers_histogram_exemplar(self):
        hist = self.reg.histogram("lat_seconds", "l", buckets=(0.1, 1.0))
        obj = Objective("lat", KIND_LATENCY, "lat_seconds", threshold_s=1.0)
        eng = _engine(self.clock, self.reg, (obj,))
        hist.observe(30.0, exemplar={"trace_id": "feedface" * 4})
        self.clock.advance(30)
        eng.evaluate()
        assert eng.firing()[0].trace_id == "feedface" * 4

    def test_history_is_bounded(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,), max_alerts=4)
        for _ in range(6):
            self._burst(errors=100, good=0)
            self.clock.advance(30)
            eng.evaluate()
            self._recover(eng)
        assert not eng.firing()
        assert len(eng.alert_history()) == 4
        # oldest evicted: the retained alerts are the newest four
        seqs = [a.seq for a in eng.alert_history()]
        assert seqs == sorted(seqs) and seqs[-1] == 6

    def test_firing_gauge_tracks_lifecycle(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        gauge = self.reg.get("notebook_slo_alert_firing")
        self._burst()
        self.clock.advance(30)
        eng.evaluate()
        assert gauge.value("errors") == 1.0
        self._recover(eng)
        assert gauge.value("errors") == 0.0

    def test_snapshot_shape(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        self._burst()
        self.clock.advance(30)
        eng.evaluate()
        snap = eng.snapshot()
        assert snap["windows"] == ["5m", "1h"]
        assert snap["objectives"]["errors"]["firing"] is True
        assert snap["firing"][0]["objective"] == "errors"
        assert snap["history"][0]["state"] == "firing"
        json.dumps(snap)  # must be a plain JSON body for /debug/alerts

    def test_verdicts(self):
        eng = _engine(self.clock, self.reg, (ERROR_OBJ,))
        self.total.labels("notebook", "success").inc(1000)
        self.total.labels("notebook", "error").inc(1)
        self.clock.advance(60)
        v = eng.verdicts()["errors"]
        assert v["met"] is True and v["events"] == 1001
        self.total.labels("notebook", "error").inc(500)
        self.clock.advance(60)
        v = eng.verdicts()["errors"]
        assert v["met"] is False and v["burn_rate"] > 1.0


# -- overlapping_attempts sweep ------------------------------------------------


def _span(controller, ns, name, start, end, attempt=1):
    """A finished fake root span shaped like tracing.Span, carrying the
    Manager's monotonic stamps."""
    return SimpleNamespace(
        name="reconcile", recording=True, parent=None,
        trace_id=f"{random.getrandbits(64):016x}", span_id="s",
        start_time=start, end_time=end,
        attributes={"controller": controller, "namespace": ns,
                    "name": name, "attempt": attempt,
                    "reconcile.result": "success",
                    "mono_start": start, "mono_end": end},
        events=[], children=[])


def _brute_force_overlaps(records):
    out = []
    by_key = {}
    for r in records:
        if r.mono_end > r.mono_start > 0.0:
            by_key.setdefault((r.object_key, r.controller), []).append(r)
    for runs in by_key.values():
        runs.sort(key=lambda r: r.mono_start)
        for i, a in enumerate(runs):
            for b in runs[i + 1:]:
                if b.mono_start < a.mono_end:
                    out.append((a, b))
    return out


def _pair_set(pairs):
    return {tuple(sorted(((p.mono_start, p.mono_end),
                          (c.mono_start, c.mono_end)))) for p, c in pairs}


class TestOverlapSweep:
    def test_long_attempt_overlapping_several(self):
        # [100,110] overlaps BOTH [101,102] and [103,104] — the shape the
        # old adjacent-pair check under-reported (it missed the second)
        fr = FlightRecorder()
        for s, e in ((100.0, 110.0), (101.0, 102.0), (103.0, 104.0)):
            fr.record(_span("notebook", "ns", "nb", s, e))
        got = fr.overlapping_attempts()
        assert len(got) == 2
        assert _pair_set(got) == {
            tuple(sorted(((100.0, 110.0), (101.0, 102.0)))),
            tuple(sorted(((100.0, 110.0), (103.0, 104.0)))),
        }

    def test_touching_endpoints_are_clean(self):
        fr = FlightRecorder()
        fr.record(_span("notebook", "ns", "nb", 100.0, 101.0))
        fr.record(_span("notebook", "ns", "nb", 101.0, 102.0))
        assert fr.overlapping_attempts() == []

    def test_distinct_keys_and_controllers_never_pair(self):
        fr = FlightRecorder()
        fr.record(_span("notebook", "ns", "a", 100.0, 110.0))
        fr.record(_span("notebook", "ns", "b", 101.0, 102.0))
        fr.record(_span("culling", "ns", "a", 101.0, 102.0))
        assert fr.overlapping_attempts() == []

    def test_unstamped_attempts_skipped(self):
        fr = FlightRecorder()
        span = _span("notebook", "ns", "nb", 100.0, 110.0)
        span.attributes["mono_start"] = 0.0
        span.attributes["mono_end"] = 0.0
        fr.record(span)
        fr.record(_span("notebook", "ns", "nb", 101.0, 102.0))
        assert fr.overlapping_attempts() == []

    def test_sweep_equals_brute_force_on_seeded_histories(self):
        rng = random.Random(20260804)
        for trial in range(20):
            fr = FlightRecorder(capacity=4096, per_object=512)
            for _ in range(rng.randrange(20, 120)):
                ctrl = rng.choice(("notebook", "odh-notebook", "culling"))
                name = f"nb-{rng.randrange(6)}"
                start = round(rng.uniform(1, 50), 6)
                end = round(start + rng.uniform(0.001, 8), 6)
                fr.record(_span(ctrl, "ns", name, start, end))
            recs = [r for recs in
                    (fr.attempts(k) for k in fr.objects()) for r in recs]
            expect = _pair_set(_brute_force_overlaps(recs))
            got = fr.overlapping_attempts()
            assert _pair_set(got) == expect, f"trial {trial}"
            assert len(got) == len(_brute_force_overlaps(recs))


# -- fleet rollup --------------------------------------------------------------


class TestFleetState:
    def _nb(self, status):
        return SimpleNamespace(
            namespace="ns", body={"status": status},
            spec={"tpu": {"accelerator": "v5e", "topology": "4x4"}})

    def test_buckets(self):
        assert fleet_state(self._nb({"sliceHealth": "Healthy"})) == "ready"
        assert fleet_state(self._nb({"sliceHealth": "Degraded"})) \
            == "degraded"
        assert fleet_state(self._nb({"sliceHealth": "Unhealthy"})) \
            == "degraded"
        assert fleet_state(self._nb(
            {"sliceHealth": "Degraded",
             "sliceRecovery": {"0": {"attempts": [{"at": 1.0}]}}})) \
            == "recovering"
        assert fleet_state(self._nb({"sliceHealth": "Scheduling"})) \
            == "scheduling"
        assert fleet_state(self._nb({"sliceHealth": "Stopped"})) == "stopped"
        assert fleet_state(self._nb(
            {"sliceHealth": "Degraded", "conditions": [
                {"type": CONDITION_RECOVERY_EXHAUSTED, "status": "True"},
            ]})) == "exhausted"
        assert fleet_state(self._nb({})) == "pending"
        assert fleet_state(self._nb({"readyReplicas": 1})) == "ready"
        assert set(FLEET_STATES) >= {
            "ready", "degraded", "recovering", "exhausted"}


class TestFleetRollup:
    def _stack(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 12, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api, manager=mgr)
        cfg = CoreConfig(enable_self_healing=False)
        setup_core_controllers(mgr, cfg, metrics)
        return api, cluster, clock, mgr, metrics

    def _ground_truth(self, api):
        totals = {s: 0 for s in FLEET_STATES}
        for nb in api.list("Notebook"):
            totals[fleet_state(nb)] += 1
        return totals

    def test_rollup_matches_apiserver_ground_truth(self):
        from kubeflow_tpu.core import constants as CC

        api, cluster, clock, mgr, metrics = self._stack()
        for i in range(3):
            api.create(Notebook.new(f"ready-{i}", "user1",
                                    tpu=TPUSpec("v5e", "4x4")).obj)
        api.create(Notebook.new("cpu", "user2").obj)
        mgr.run_until_idle()
        # degrade one slice (self-healing off so it STAYS degraded)
        cluster.fail_pod("user1", "ready-0-1")
        mgr.run_until_idle()
        # stop another
        nb = api.get("Notebook", "user1", "ready-1")
        nb.metadata.annotations[CC.STOP_ANNOTATION] = "true"
        api.update(nb)
        mgr.settle(max_seconds=600.0)
        # and one never reconciled at all (created after the last drain)
        api.create(Notebook.new("fresh", "user3",
                                tpu=TPUSpec("v5e", "4x4")).obj)

        snap = metrics.fleet_snapshot()
        truth = self._ground_truth(api)
        assert snap["totals"] == truth
        assert snap["notebooks"] == sum(truth.values())
        assert snap["namespaces"]["user1"]["degraded"] == 1
        assert snap["namespaces"]["user1"]["stopped"] == 1
        assert snap["shapes"]["v5e-4x4"]["ready"] == 1
        # the CPU notebook contributes to its namespace but to no shape
        assert snap["namespaces"]["user2"] == {"ready": 1}
        assert "scheduling" not in snap["shapes"]["v5e-4x4"] or \
            snap["shapes"]["v5e-4x4"]["scheduling"] >= 0

        # incremental: a state transition moves the counts, no rescan
        mgr.run_until_idle()  # fresh notebook converges
        snap2 = metrics.fleet_snapshot()
        assert snap2["totals"] == self._ground_truth(api)
        assert snap2["totals"]["ready"] == snap["totals"]["ready"] + 1

    def test_rollup_without_cache_falls_back_to_lists(self):
        api = ApiServer()
        metrics = NotebookMetrics(api)  # no manager, no cache
        api.create(Notebook.new("a", "ns1").obj)
        api.create(Notebook.new("b", "ns2").obj)
        snap = metrics.fleet_snapshot()
        assert snap["totals"]["pending"] == 2
        assert snap["namespaces"] == {"ns1": {"pending": 1},
                                      "ns2": {"pending": 1}}

    def test_fleet_endpoint_over_http(self):
        from kubeflow_tpu.main import serve_http

        api, cluster, clock, mgr, metrics = self._stack()
        api.create(Notebook.new("nb", "user1",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        server = serve_http(0, mgr, metrics)
        port = server.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/fleet", timeout=5) as r:
                body = json.loads(r.read().decode())
            assert body["totals"] == self._ground_truth(api)
            assert body["namespaces"]["user1"] == {"ready": 1}
            # alerts + profile endpoints answer too (profiler disabled)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/alerts", timeout=5) as r:
                alerts = json.loads(r.read().decode())
            assert alerts == {"enabled": False,
                              "error": "no SLO engine attached to this "
                                       "manager"}
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile",
                    timeout=5) as r:
                prof = json.loads(r.read().decode())
            assert prof["enabled"] is False
        finally:
            server.shutdown()
            mgr.stop()


# -- continuous profiler -------------------------------------------------------


class TestProfiler:
    def test_attribution_from_live_span_stack(self):
        root = SimpleNamespace(attributes={"controller": "notebook"})
        child = SimpleNamespace(attributes={"phase": "render"})
        assert attribute((root, child)) == ("notebook", "render")
        assert attribute((root,)) == ("notebook", "reconcile")
        assert attribute(()) == (UNATTRIBUTED, UNATTRIBUTED)
        # innermost phase wins (odh auth nested inside routing)
        inner = SimpleNamespace(attributes={"phase": "auth"})
        outer = SimpleNamespace(attributes={"phase": "routing"})
        assert attribute((root, outer, inner)) == ("notebook", "auth")

    def test_sample_once_attributes_spanned_thread(self):
        reg = Registry()
        prof = ContinuousProfiler(registry=reg)
        tracer = tracing.get_tracer("test")
        ready, done = threading.Event(), threading.Event()

        def worker():
            with tracer.start_span("reconcile",
                                   {"controller": "notebook"}):
                with tracer.start_span("render", {"phase": "render"}):
                    ready.set()
                    done.wait(5.0)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        assert ready.wait(5.0)
        try:
            assert prof.sample_once() >= 1
        finally:
            done.set()
            t.join(timeout=5.0)
        snap = prof.snapshot()
        assert snap["samples_total"] >= 1
        assert any(s["controller"] == "notebook" and s["phase"] == "render"
                   and "test_slo.py:worker" in s["stack"]
                   for s in snap["stacks"]), snap["stacks"]
        # counter fed through the registry
        assert reg.get("notebook_profiler_samples_total").value() >= 1
        # the worker finished: its live-stack entry is gone
        assert not any("worker" in str(s)
                       for s in tracing.live_span_stacks().values())

    def test_collapsed_format(self):
        prof = ContinuousProfiler()
        prof._record("notebook", "apply", "a.py:f;b.py:g")
        prof._record("notebook", "apply", "a.py:f;b.py:g")
        prof._record("-", "-", "main.py:loop")
        text = prof.collapsed()
        assert "notebook;apply;a.py:f;b.py:g 2" in text.splitlines()
        assert "-;-;main.py:loop 1" in text.splitlines()

    def test_store_is_bounded(self):
        prof = ContinuousProfiler(max_stacks=3)
        for i in range(10):
            prof._record("c", "p", f"stack-{i}")
        prof._record("c", "p", "stack-0")  # existing key still counts
        snap = prof.snapshot()
        assert snap["distinct_stacks"] == 3
        assert snap["overflow_samples"] == 7
        assert snap["samples_total"] == 11

    def test_overhead_ratio_measured(self):
        reg = Registry()
        prof = ContinuousProfiler(registry=reg, interval_s=0.002)
        assert prof.overhead_ratio() == 0.0  # not started yet
        prof.start()
        try:
            import time
            deadline = time.monotonic() + 2.0
            while prof.passes < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            prof.stop()
        assert prof.passes >= 5
        ratio = prof.overhead_ratio()
        assert 0.0 < ratio < 0.5
        # the gauge serves the same number via set_function
        assert reg.get("notebook_profiler_overhead_ratio").collect()[()] \
            == pytest.approx(ratio, abs=0.05)

    def test_off_by_default_in_wired_stack(self):
        from kubeflow_tpu.main import build_manager

        mgr, api, cluster, metrics = build_manager(
            core_cfg=CoreConfig.from_env({}))
        try:
            assert mgr.profiler is None
            # families present (drift-golden stability) even while off
            text = metrics.scrape()
            assert "# TYPE notebook_profiler_overhead_ratio gauge" in text
            assert "notebook_profiler_overhead_ratio 0" in text
        finally:
            mgr.stop()

    def test_enabled_via_config(self):
        from kubeflow_tpu.main import build_manager

        mgr, api, cluster, metrics = build_manager(
            core_cfg=CoreConfig.from_env(
                {"ENABLE_CONTINUOUS_PROFILER": "true",
                 "PROFILER_INTERVAL_MS": "2"}))
        try:
            assert mgr.profiler is not None and mgr.profiler.running
            assert mgr.profiler.interval_s == pytest.approx(0.002)
        finally:
            mgr.profiler.stop()
            mgr.stop()


# -- diagnostics bundle --------------------------------------------------------


class TestDiagnoseBundle:
    def _converged_stack(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api, manager=mgr)
        cfg = CoreConfig()
        setup_core_controllers(mgr, cfg, metrics)
        engine = SLOEngine(default_objectives(cfg),
                           [metrics.registry, mgr.metrics_registry],
                           clock=clock, recorder=mgr.flight_recorder)
        mgr.slo_engine = engine
        metrics.attach_slo(engine)
        api.create(Notebook.new("nb", "user1",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        return api, mgr, metrics

    def test_redacted_config(self):
        env = {
            "WORKQUEUE_WORKERS": "8",
            "SLO_RECONCILE_ERROR_RATE": "0.01",
            "OTEL_EXPORTER_OTLP_TOKEN": "hunter2",
            "CHECKPOINT_STORE_SECRET": "s3cr3t",
            "HOME": "/root",          # not config surface: excluded
            "PATH": "/usr/bin",
        }
        red = redacted_config(env)
        assert red["WORKQUEUE_WORKERS"] == "8"
        assert red["SLO_RECONCILE_ERROR_RATE"] == "0.01"
        assert red["OTEL_EXPORTER_OTLP_TOKEN"] == REDACTED
        assert red["CHECKPOINT_STORE_SECRET"] == REDACTED
        assert "HOME" not in red and "PATH" not in red

    def test_local_bundle_reconstructs_slowest_attempt(self):
        api, mgr, metrics = self._converged_stack()
        bundle = collect_local(mgr, metrics,
                               env={"WORKQUEUE_WORKERS": "1"})
        json.dumps(bundle, default=str)  # one serializable artifact
        assert bundle["bundle_format"] == 1
        assert "# TYPE controller_runtime_reconcile_total counter" in \
            bundle["metrics"]
        assert bundle["fleet"]["totals"]["ready"] == 1
        assert bundle["alerts"]["firing"] == []
        assert bundle["slo_verdicts"]["reconcile_errors"]["met"] is True
        assert bundle["config"] == {"WORKQUEUE_WORKERS": "1"}
        assert bundle["workqueue"]["depth"] == 0
        # the slowest retained attempt is fully reconstructable from the
        # bundle alone: summary -> trace id -> span tree with phases
        slowest = bundle["reconciles"]["slowest"][0]
        tree = bundle["traces"][slowest["trace_id"]]
        assert tree["spans"], slowest
        roots = [s for s in tree["spans"]
                 if s["span_id"] == slowest["span_id"]]
        assert len(roots) == 1
        assert slowest["phases"].keys() <= {
            c["attributes"].get("phase", c["name"])
            for c in roots[0]["children"]} | set(slowest["phases"])
        mgr.stop()

    def test_http_bundle_and_cli(self, tmp_path):
        from kubeflow_tpu.main import serve_http

        api, mgr, metrics = self._converged_stack()
        server = serve_http(0, mgr, metrics)
        port = server.server_address[1]
        try:
            bundle = collect_http(f"http://127.0.0.1:{port}")
            assert bundle["source"].endswith(str(port))
            assert bundle["fleet"]["totals"]["ready"] == 1
            slowest = bundle["reconciles"]["slowest"][0]
            assert bundle["traces"][slowest["trace_id"]]["spans"]
            assert bundle["profile"]["enabled"] is False

            out = tmp_path / "bundle.json"
            rc = diagnose_main(["--addr", f"127.0.0.1:{port}",
                                "--out", str(out)])
            assert rc == 0
            written = json.loads(out.read_text())
            assert written["bundle_format"] == 1
            assert written["reconciles"]["recorded_total"] > 0
        finally:
            server.shutdown()
            mgr.stop()

    def test_cli_unreachable_manager_fails_cleanly(self, tmp_path):
        rc = diagnose_main(["--addr", "127.0.0.1:1",  # nothing listens
                            "--out", str(tmp_path / "b.json"),
                            "--timeout", "0.5"])
        assert rc == 1
        assert not (tmp_path / "b.json").exists()


class TestDiagnoseMerge:
    """ops.diagnose --merge: the offline cross-process double-reconcile
    sweep over several managers' bundles."""

    @staticmethod
    def _attempt(obj, span_id, mono_start, mono_end, controller="core"):
        return {"object": obj, "controller": controller, "attempt": 0,
                "result": "success", "start_time": 0.0, "end_time": 0.0,
                "duration_s": 0.1, "phases": {}, "trace_id": "t-" + span_id,
                "span_id": span_id, "error": "", "faults": [],
                "mono_start": mono_start, "mono_end": mono_end}

    @staticmethod
    def _bundle(attempts, slowest=()):
        return {"bundle_format": 1,
                "reconciles": {"attempts": list(attempts),
                               "slowest": list(slowest), "errored": []}}

    def test_merge_dedupes_ring_and_retained_sets(self):
        from kubeflow_tpu.ops.diagnose import merge_records

        a = self._attempt("u1/nb", "s1", 10.0, 11.0)
        # the same attempt retained in the ring AND the slowest set of
        # the same bundle must count once in the merged history
        records = merge_records([self._bundle([a], slowest=[a])])
        assert len(records) == 1
        assert records[0].object_key == "u1/nb"

    def test_merge_flags_cross_bundle_overlap(self, tmp_path, capsys):
        from kubeflow_tpu.ops.diagnose import merge_overlaps

        # replica A and replica B each look clean in isolation — the
        # overlap only exists across their merged histories
        bundle_a = self._bundle([self._attempt("u1/nb", "a1", 10.0, 12.0)])
        bundle_b = self._bundle([self._attempt("u1/nb", "b1", 11.0, 13.0)])
        assert merge_overlaps([bundle_a]) == []
        assert merge_overlaps([bundle_b]) == []
        pairs = merge_overlaps([bundle_a, bundle_b])
        assert len(pairs) == 1

        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(bundle_a))
        pb.write_text(json.dumps(bundle_b))
        rc = diagnose_main(["--merge", str(pa), str(pb)])
        assert rc == 1, "overlapping bundles must fail the merge sweep"
        out = capsys.readouterr().out
        assert "1 overlapping pairs" in out and "OVERLAP core u1/nb" in out

    def test_merge_clean_bundles_pass(self, tmp_path, capsys):
        # same key, touching endpoints across replicas: a handoff, not a
        # double-reconcile
        bundle_a = self._bundle([self._attempt("u1/nb", "a1", 10.0, 12.0)])
        bundle_b = self._bundle([self._attempt("u1/nb", "b1", 12.0, 13.0),
                                 self._attempt("u2/nb", "b2", 10.5, 11.5)])
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(bundle_a))
        pb.write_text(json.dumps(bundle_b))
        rc = diagnose_main(["--merge", str(pa), str(pb)])
        assert rc == 0
        assert "3 distinct attempts, 0 overlapping pairs" in \
            capsys.readouterr().out

    def test_merge_unreadable_bundle_fails_cleanly(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert diagnose_main(["--merge", str(bad)]) == 1
        assert diagnose_main(
            ["--merge", str(tmp_path / "missing.json")]) == 1


class TestLoadtestSLOVerdicts:
    def test_run_fleet_records_slo_verdicts(self):
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location(
            "loadtest_convergence",
            Path(__file__).parent.parent / "loadtest" / "convergence.py")
        conv = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(conv)
        result = conv.run_fleet(6, 1, compute_state=False)
        slo = result["slo"]
        assert {"time_to_ready", "event_to_reconcile",
                "reconcile_errors", "recovery_duration"} <= set(slo)
        for name, verdict in slo.items():
            assert verdict["met"] is True, (name, verdict)
        json.dumps(result)  # --out writes this verbatim
