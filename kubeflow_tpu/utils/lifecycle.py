"""Per-notebook lifecycle stage ledger: critical-path attribution of
event->ready wall time.

Every latency signal so far is either a point-in-time scrape (histograms,
SLO burn windows) or a per-attempt trace — none of them answers "where did
THIS notebook's 40 seconds between the create event and Ready actually
go?".  This module assembles that answer from hooks that already exist:
the Manager feeds each finished reconcile root span (the same call site
that feeds the flight recorder), and the ledger folds the attempt stream
into a **causally ordered, non-overlapping partition** of each notebook's
event->ready window:

  event cause -> queue_wait -> [handoff_wait] -> schedule_warm|schedule_cold
    -> render/apply/status (in-attempt phase spans) -> pod_schedule
    -> pod_start -> retry_backoff / recovery_wait excursions -> ready

Keyed ``(namespace, name, generation)`` so a spec update opens a fresh
ledger entry instead of polluting the finished one; bounded like the
flight recorder (LRU over ``max_notebooks``).  Post-ready recover/migrate
spans are recorded as excursions — attributed to their stage histograms
but excluded from the conservation window.

**Conservation is the falsifiability contract**: the partition is built by
a watermark sweep over all attempts (notebook controller AND scheduler —
per-key serialization is per (controller, key), so their windows may
overlap and must be clipped), which makes

    sum(attributed stage durations) == ready_ts - cause_ts

hold *by construction*; any double-count, overlap, or leak in the
bookkeeping breaks the equality, and `conservation()` / `violations()`
expose the residual against an independently measured wall time.  The
loadtest gates on it (<= 5% relative error) and the chaos soak asserts it
across kills, handoffs, and recovery excursions.

Stage durations export as ``notebook_stage_duration_seconds{stage}``
histograms (exemplar trace ids resolve at /debug/traces) and as a
fleet-wide critical-path ranking — mean and p99 contribution per stage —
at /debug/criticalpath.  Utils idiom: plain locks, injected timestamps
only (all times come from span/event stamps, which follow
``tracing.set_clock``), O(bounds) memory, never raises into the reconcile
loop's feed path.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

from .metrics import Registry

# The closed stage vocabulary (bounded label set — Prometheus cardinality
# discipline).  `schedule_wait` is an internal placeholder resolved to
# warm/cold at finalize; it never leaves the ledger.
STAGE_QUEUE_WAIT = "queue_wait"
# time parked behind the tenancy admission gate (quota / fair share /
# preemption fence, core/scheduler.py) — distinct from queue_wait (the
# workqueue) and schedule_* (capacity): the gang was not even in line
STAGE_QUOTA_WAIT = "quota_wait"
STAGE_HANDOFF_WAIT = "handoff_wait"
STAGE_SCHEDULE_WARM = "schedule_warm"
STAGE_SCHEDULE_COLD = "schedule_cold"
STAGE_RENDER = "render"
STAGE_APPLY = "apply"
STAGE_STATUS = "status"
STAGE_POD_SCHEDULE = "pod_schedule"
STAGE_POD_START = "pod_start"
STAGE_RETRY_BACKOFF = "retry_backoff"
STAGE_RECOVERY_WAIT = "recovery_wait"
STAGE_RECOVER = "recover"
STAGE_MIGRATE = "migrate"
STAGE_PROMOTE = "promote"
STAGE_OTHER = "reconcile_other"

_SCHEDULE_WAIT = "_schedule_wait"  # placeholder, resolved warm/cold

STAGES = (
    STAGE_QUEUE_WAIT, STAGE_QUOTA_WAIT, STAGE_HANDOFF_WAIT,
    STAGE_SCHEDULE_WARM,
    STAGE_SCHEDULE_COLD, STAGE_RENDER, STAGE_APPLY, STAGE_STATUS,
    STAGE_POD_SCHEDULE, STAGE_POD_START, STAGE_RETRY_BACKOFF,
    STAGE_RECOVERY_WAIT, STAGE_RECOVER, STAGE_MIGRATE, STAGE_PROMOTE,
    STAGE_OTHER,
)

# phase attribute (controllers' child spans) -> ledger stage
_PHASE_STAGES = {
    "render": STAGE_RENDER,
    "apply": STAGE_APPLY,
    "status": STAGE_STATUS,
    "schedule": _SCHEDULE_WAIT,
    "recover": STAGE_RECOVER,
    "migrate": STAGE_MIGRATE,
    "promote": STAGE_PROMOTE,
}

# Ready-time spans minutes at fleet scale, far past reconcile-time's
# DefBuckets — these cover 50ms render phases through 10-minute cold
# provisioning waits.
STAGE_BUCKETS = (0.05, 0.25, 1.0, 5.0, 15.0, 30.0, 60.0,
                 120.0, 300.0, 600.0)

# Controllers whose attempts reconcile a Notebook key and therefore
# belong on its lifecycle timeline (event-reemit reconciles Events,
# warm-pool reconciles TPUWarmPool objects).
_TRACKED_CONTROLLERS = ("notebook", "slice-scheduler")


def register_lifecycle_metrics(registry: Registry):
    """The lifecycle metric family (registered by NotebookMetrics so the
    inventory is stable whether or not a ledger is attached; the ledger
    re-registers identically and gets the same object back)."""
    return registry.histogram(
        "notebook_stage_duration_seconds",
        "Attributed duration of one lifecycle stage on a notebook's "
        "event->ready critical path (conserving partition; see "
        "/debug/criticalpath)",
        labels=("stage",), buckets=STAGE_BUCKETS)


@dataclass
class _Attempt:
    """One reconcile attempt projected onto a notebook's timeline."""

    controller: str
    manager_id: str
    start: float
    end: float
    trace_id: str
    # in-attempt (start, end, stage) phase segments, sorted by (start, end)
    segments: list = field(default_factory=list)
    # stage of the idle gap AFTER this attempt; None preserves the prior
    next_hint: Optional[str] = None
    ready_ts: Optional[float] = None
    saw_cold: bool = False


@dataclass
class _Entry:
    """Ledger state for one (ns, name, generation)."""

    namespace: str
    name: str
    generation: int
    cause_ts: Optional[float] = None
    attempts: list = field(default_factory=list)
    finalized: bool = False
    ready_ts: float = 0.0
    wall_s: float = 0.0
    attributed_s: float = 0.0
    stages: dict = field(default_factory=dict)
    trace_id: str = ""


def _walk_spans(span):
    yield span
    for child in span.children:
        yield from _walk_spans(child)


class LifecycleLedger:
    """See module docstring.  Fed by the Manager with each finished
    reconcile root span; one ledger may serve a whole sharded fleet
    (every replica's manager points at the same object), which is what
    lets handoff/adoption waits be attributed: a manager-id change
    between consecutive attempts marks the gap as handoff_wait."""

    def __init__(self, registry: Optional[Registry] = None,
                 max_notebooks: int = 4096,
                 samples_per_stage: int = 2048,
                 keep_conservation: int = 4096,
                 tolerance: float = 0.05,
                 excursions_per_notebook: int = 32) -> None:
        self.max_notebooks = max_notebooks
        self.samples_per_stage = samples_per_stage
        self.tolerance = tolerance
        self.excursions_per_notebook = excursions_per_notebook
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # (ns, name) -> bounded ring of post-ready excursion records, so
        # recovery/migrate/promote churn is explainable after the fact
        # (excursions_total alone says "how many", not "what")
        self._excursion_log: "OrderedDict[tuple, deque]" = OrderedDict()
        # latest observed generation per (ns, name) — scheduler attempts
        # carry it too, but a stale cache read may omit it
        self._gen: "OrderedDict[tuple, int]" = OrderedDict()
        # aggregates over finalized ledgers
        self._stage_total: dict[str, float] = {}
        self._stage_count: dict[str, int] = {}
        self._stage_samples: dict[str, deque] = {}
        # ns -> {"ready": deque, "stages": {stage: [count, total]}}
        self._ns: dict[str, dict] = {}
        self._conservation: deque = deque(maxlen=keep_conservation)
        self._violations: deque = deque(maxlen=keep_conservation)
        self.finalized_total = 0
        self.excursions_total = 0
        self._max_rel_err = 0.0
        self._hist = (register_lifecycle_metrics(registry)
                      if registry is not None else None)

    # -- write side (Manager, on root-span completion) -------------------------
    def observe_attempt(self, rec, root_span, manager_id: str = "") -> None:
        """Fold one finished reconcile attempt into its notebook's ledger.
        `rec` is the FlightRecorder's AttemptRecord for the same span (the
        Manager produces both at one call site); `manager_id` identifies
        the feeding replica so shard handoffs are attributable."""
        if root_span is None or rec is None:
            return
        attrs = root_span.attributes
        controller = str(attrs.get("controller", ""))
        if controller not in _TRACKED_CONTROLLERS:
            return
        ns = str(attrs.get("namespace", ""))
        name = str(attrs.get("name", ""))
        if not name:
            return
        attempt = self._project(controller, manager_id, rec, root_span)
        with self._lock:
            gen = int(attrs.get("generation", 0) or 0)
            nskey = (ns, name)
            if gen > 0:
                self._gen[nskey] = gen
                self._gen.move_to_end(nskey)
                while len(self._gen) > self.max_notebooks:
                    self._gen.popitem(last=False)
            else:
                gen = self._gen.get(nskey, 1)
            key = (ns, name, gen)
            entry = self._entries.get(key)
            if entry is not None and entry.finalized:
                self._record_excursions(entry, attempt)
                return
            if entry is None:
                entry = _Entry(namespace=ns, name=name, generation=gen)
                self._entries[key] = entry
                while len(self._entries) > self.max_notebooks:
                    self._entries.popitem(last=False)
            self._entries.move_to_end(key)
            cause = attrs.get("cause_ts")
            if entry.cause_ts is None:
                entry.cause_ts = (float(cause) if cause is not None
                                  else rec.start_time)
            entry.attempts.append(attempt)
            entry.trace_id = attempt.trace_id or entry.trace_id
            if attempt.ready_ts is not None:
                self._finalize(key, entry, attempt.ready_ts)

    def _project(self, controller: str, manager_id: str, rec,
                 root_span) -> _Attempt:
        """Summarize one root span tree into an _Attempt: in-attempt phase
        segments plus the hint for what the notebook waits on next."""
        a = _Attempt(controller=controller, manager_id=manager_id,
                     start=rec.start_time, end=rec.end_time,
                     trace_id=rec.trace_id)
        waiting_on = ""
        saw_backoff_wait = False
        saw_queued = False
        for span in _walk_spans(root_span):
            stage = _PHASE_STAGES.get(str(span.attributes.get("phase", "")))
            if stage is not None and span is not root_span:
                a.segments.append((span.start_time, span.end_time, stage))
            for ev in span.events:
                if ev.name == "notebook.ready":
                    a.ready_ts = ev.timestamp
                elif ev.name == "notebook.waiting":
                    waiting_on = str(ev.attributes.get("on", ""))
                elif ev.name == "schedule.wait":
                    a.saw_cold = True
                elif ev.name == "schedule.queued":
                    saw_queued = True
                elif ev.name == "schedule.placed":
                    waiting_on = "placed"
                elif ev.name == "recovery.backoff_wait":
                    saw_backoff_wait = True
        a.segments.sort(key=lambda s: (s[0], s[1]))
        result = rec.result
        if saw_queued or waiting_on == "quota_wait":
            # the admission gate parked the gang this attempt: the idle
            # gap that follows is quota_wait, regardless of the requeue
            # the gate returns to re-examine the line
            a.next_hint = STAGE_QUOTA_WAIT
        elif result in ("error", "requeue"):
            a.next_hint = STAGE_RETRY_BACKOFF
        elif saw_backoff_wait:
            a.next_hint = STAGE_RECOVERY_WAIT
        elif a.saw_cold or waiting_on == "scheduling":
            a.next_hint = _SCHEDULE_WAIT
        elif waiting_on == "placed":
            a.next_hint = STAGE_QUEUE_WAIT
        elif waiting_on == "pod_schedule":
            a.next_hint = STAGE_POD_SCHEDULE
        elif waiting_on == "pod_start":
            a.next_hint = STAGE_POD_START
        return a

    # -- the conserving partition ---------------------------------------------
    def _finalize(self, key: tuple, entry: _Entry, ready_ts: float) -> None:
        """Watermark sweep: partition [cause_ts, ready_ts] across every
        recorded attempt's execution window and phase segments, classify
        the gaps by the standing wait hint, and fold the result into the
        fleet aggregates.  Called under the lock."""
        t0 = entry.cause_ts if entry.cause_ts is not None else ready_ts
        tr = max(ready_ts, t0)
        attempts = sorted(entry.attempts, key=lambda a: (a.start, a.end))
        saw_cold = any(a.saw_cold for a in attempts)
        stages: dict[str, float] = {}

        def add(stage: str, dur: float) -> None:
            if dur > 0.0:
                if stage == _SCHEDULE_WAIT:
                    stage = (STAGE_SCHEDULE_COLD if saw_cold
                             else STAGE_SCHEDULE_WARM)
                stages[stage] = stages.get(stage, 0.0) + dur

        def clip(t: float, lo: float) -> float:
            return min(max(t, lo), tr)

        watermark = t0
        hint: Optional[str] = None
        prev: Optional[_Attempt] = None
        for a in attempts:
            gap_stage = STAGE_QUEUE_WAIT if prev is None \
                else (hint or STAGE_QUEUE_WAIT)
            if prev is not None and a.manager_id and prev.manager_id \
                    and a.manager_id != prev.manager_id:
                gap_stage = STAGE_HANDOFF_WAIT
            start = clip(a.start, watermark)
            add(gap_stage, start - watermark)
            watermark = start
            for (s, e, st) in a.segments:
                s2, e2 = clip(s, watermark), clip(e, watermark)
                add(STAGE_OTHER, s2 - watermark)
                add(st, e2 - s2)
                watermark = max(watermark, e2)
            end = clip(a.end, watermark)
            add(STAGE_OTHER, end - watermark)
            watermark = max(watermark, end)
            if a.next_hint is not None:
                hint = a.next_hint
            prev = a
        add(hint or STAGE_OTHER, tr - watermark)

        entry.finalized = True
        entry.ready_ts = tr
        entry.stages = stages
        entry.wall_s = tr - t0
        entry.attributed_s = sum(stages.values())
        entry.attempts = []  # the partition replaces the raw attempt log
        self.finalized_total += 1

        rel_err = (abs(entry.attributed_s - entry.wall_s)
                   / entry.wall_s) if entry.wall_s > 1e-9 else 0.0
        self._max_rel_err = max(self._max_rel_err, rel_err)
        record = {
            "namespace": entry.namespace, "name": entry.name,
            "generation": entry.generation, "wall_s": entry.wall_s,
            "attributed_s": entry.attributed_s, "rel_err": rel_err,
            "trace_id": entry.trace_id,
        }
        self._conservation.append(record)
        if rel_err > self.tolerance:
            self._violations.append(record)

        exemplar = ({"trace_id": entry.trace_id}
                    if entry.trace_id else None)
        for stage, dur in stages.items():
            self._stage_total[stage] = \
                self._stage_total.get(stage, 0.0) + dur
            self._stage_count[stage] = self._stage_count.get(stage, 0) + 1
            samples = self._stage_samples.get(stage)
            if samples is None:
                samples = deque(maxlen=self.samples_per_stage)
                self._stage_samples[stage] = samples
            samples.append(dur)
            if self._hist is not None:
                self._hist.labels(stage).observe(dur, exemplar=exemplar)
        nsagg = self._ns.get(entry.namespace)
        if nsagg is None:
            nsagg = {"ready": deque(maxlen=self.samples_per_stage),
                     "stages": {}}
            self._ns[entry.namespace] = nsagg
        nsagg["ready"].append(entry.wall_s)
        for stage, dur in stages.items():
            st = nsagg["stages"].setdefault(stage, [0, 0.0])
            st[0] += 1
            st[1] += dur

    def _record_excursions(self, entry: _Entry, attempt: _Attempt) -> None:
        """Post-ready recover/migrate/promote work: attributed to its stage
        histogram but outside the conserved event->ready window.  Called
        under the lock."""
        exemplar = ({"trace_id": attempt.trace_id}
                    if attempt.trace_id else None)
        nskey = (entry.namespace, entry.name)
        for (s, e, stage) in attempt.segments:
            if stage not in (STAGE_RECOVER, STAGE_MIGRATE, STAGE_PROMOTE):
                continue
            dur = max(e - s, 0.0)
            self.excursions_total += 1
            ring = self._excursion_log.get(nskey)
            if ring is None:
                ring = deque(maxlen=self.excursions_per_notebook)
                self._excursion_log[nskey] = ring
                while len(self._excursion_log) > self.max_notebooks:
                    self._excursion_log.popitem(last=False)
            self._excursion_log.move_to_end(nskey)
            ring.append({
                "stage": stage, "duration_s": dur, "start": s, "end": e,
                "trace_id": attempt.trace_id,
                "generation": entry.generation,
            })
            self._stage_total[stage] = \
                self._stage_total.get(stage, 0.0) + dur
            self._stage_count[stage] = self._stage_count.get(stage, 0) + 1
            samples = self._stage_samples.get(stage)
            if samples is None:
                samples = deque(maxlen=self.samples_per_stage)
                self._stage_samples[stage] = samples
            samples.append(dur)
            if self._hist is not None:
                self._hist.labels(stage).observe(dur, exemplar=exemplar)

    # -- read side (/debug/criticalpath, loadtest, tests) ----------------------
    @staticmethod
    def _p99(samples) -> float:
        """Nearest-rank p99 (same convention as loadtest/convergence.py)."""
        if not samples:
            return 0.0
        ordered = sorted(samples)
        n = len(ordered)
        return ordered[min(max((99 * n + 99) // 100 - 1, 0), n - 1)]

    def ranking(self) -> list[dict]:
        """Fleet-wide critical path: per stage, the mean and p99
        contribution to event->ready, ranked by total attributed time."""
        with self._lock:
            grand = sum(self._stage_total.values()) or 1.0
            out = []
            for stage, total in self._stage_total.items():
                count = self._stage_count.get(stage, 0)
                samples = self._stage_samples.get(stage, ())
                out.append({
                    "stage": stage,
                    "count": count,
                    "total_s": total,
                    "mean_s": total / count if count else 0.0,
                    "p99_s": self._p99(samples),
                    "share": total / grand,
                })
            out.sort(key=lambda r: r["total_s"], reverse=True)
            return out

    def conservation(self) -> dict:
        """The falsifiability summary: every finalized ledger's attributed
        sum vs its measured event->ready wall time."""
        with self._lock:
            recs = list(self._conservation)
            mean_err = (sum(r["rel_err"] for r in recs) / len(recs)
                        if recs else 0.0)
            return {
                "finalized": self.finalized_total,
                "checked": len(recs),
                "violations": len(self._violations),
                "tolerance": self.tolerance,
                "max_rel_err": self._max_rel_err,
                "mean_rel_err": mean_err,
            }

    def violations(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._violations]

    def conservation_records(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._conservation]

    def namespace_rollup(self) -> dict:
        """Per-namespace ready-time and stage-latency aggregates — the
        'tenants' view in /debug/fleet."""
        with self._lock:
            out = {}
            for ns, agg in self._ns.items():
                ready = agg["ready"]
                out[ns] = {
                    "ready_count": len(ready),
                    "ready_mean_s": (sum(ready) / len(ready)
                                     if ready else 0.0),
                    "ready_p99_s": self._p99(ready),
                    "stages": {
                        stage: {"count": c, "total_s": t,
                                "mean_s": t / c if c else 0.0}
                        for stage, (c, t) in sorted(agg["stages"].items())
                    },
                }
            return out

    def pending_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values() if not e.finalized)

    def entry(self, namespace: str, name: str,
              generation: int) -> Optional[dict]:
        """One notebook's finalized partition (tests, /debug drill-down)."""
        with self._lock:
            e = self._entries.get((namespace, name, generation))
            if e is None:
                return None
            return {
                "namespace": e.namespace, "name": e.name,
                "generation": e.generation, "finalized": e.finalized,
                "cause_ts": e.cause_ts, "ready_ts": e.ready_ts,
                "wall_s": e.wall_s, "attributed_s": e.attributed_s,
                "stages": dict(e.stages), "trace_id": e.trace_id,
                "attempts": len(e.attempts),
            }

    def latest_entry(self, namespace: str, name: str) -> Optional[dict]:
        """The notebook's most recent generation's partition (the
        diagnosis engine's entry point — callers don't know generations)."""
        with self._lock:
            gen = self._gen.get((namespace, name))
        if gen is None:
            return None
        return self.entry(namespace, name, gen)

    def excursions(self, namespace: str, name: str) -> list[dict]:
        """The bounded post-ready excursion ring for one notebook:
        recover/migrate/promote records with stage, duration, trace_id."""
        with self._lock:
            ring = self._excursion_log.get((namespace, name))
            return [dict(r) for r in ring] if ring else []

    def stage_p99s(self) -> dict[str, float]:
        """Stage -> p99 seconds over the retained samples (the TSDB's
        per-scrape stage series)."""
        with self._lock:
            return {stage: self._p99(samples)
                    for stage, samples in self._stage_samples.items()}

    def snapshot(self) -> dict:
        """The /debug/criticalpath body."""
        base = {
            "bounds": {
                "max_notebooks": self.max_notebooks,
                "samples_per_stage": self.samples_per_stage,
            },
            "stages": list(STAGES),
            "ranking": self.ranking(),
            "conservation": self.conservation(),
            "violations": self.violations(),
            "namespaces": self.namespace_rollup(),
        }
        with self._lock:
            base["pending"] = sum(
                1 for e in self._entries.values() if not e.finalized)
            base["excursions_total"] = self.excursions_total
            base["excursion_objects"] = len(self._excursion_log)
        return base

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._gen.clear()
            self._excursion_log.clear()
            self._stage_total.clear()
            self._stage_count.clear()
            self._stage_samples.clear()
            self._ns.clear()
            self._conservation.clear()
            self._violations.clear()
            self.finalized_total = 0
            self.excursions_total = 0
            self._max_rel_err = 0.0


__all__ = ["LifecycleLedger", "register_lifecycle_metrics", "STAGES",
           "STAGE_BUCKETS"]
