"""Culling controller tests (reference culling_controller_test.go:13-142 +
idleness flow through the manager with a fake clock)."""

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core import culler
from kubeflow_tpu.core.culling_controller import (
    CHECKPOINT_COMPLETE_ANNOTATION,
    setup_culling,
)
from kubeflow_tpu.core.jupyter import FakeJupyterState
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager, ObjectMeta
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig


class TestCullerLib:
    def test_stop_annotation_roundtrip(self):
        clock = FakeClock()
        meta = ObjectMeta()
        assert not culler.stop_annotation_is_set(meta)
        culler.set_stop_annotation(meta, clock)
        assert culler.stop_annotation_is_set(meta)
        culler.remove_stop_annotation(meta)
        assert not culler.stop_annotation_is_set(meta)

    def test_idleness_math(self):
        clock = FakeClock()
        meta = ObjectMeta()
        culler.initialize_annotations(meta, clock)
        assert not culler.notebook_is_idle(meta, clock, cull_idle_min=60)
        clock.advance(59 * 60)
        assert not culler.notebook_is_idle(meta, clock, cull_idle_min=60)
        clock.advance(2 * 60)
        assert culler.notebook_is_idle(meta, clock, cull_idle_min=60)
        # stopped notebooks are never "idle"
        culler.set_stop_annotation(meta, clock)
        assert not culler.notebook_is_idle(meta, clock, cull_idle_min=60)

    def test_busy_kernel_bumps_activity_to_now(self):
        clock = FakeClock()
        meta = ObjectMeta()
        culler.initialize_annotations(meta, clock)
        clock.advance(3600)
        kernels = [
            {"execution_state": "idle", "last_activity": "2020-01-01T00:00:00Z"},
            {"execution_state": "busy", "last_activity": "2020-01-01T00:00:00Z"},
        ]
        culler.update_last_activity_from_kernels(meta, kernels, clock)
        assert meta.annotations[C.LAST_ACTIVITY_ANNOTATION] == clock.now_iso()

    def test_idle_kernels_use_most_recent_but_never_backwards(self):
        clock = FakeClock()
        meta = ObjectMeta()
        meta.annotations[C.LAST_ACTIVITY_ANNOTATION] = "2023-06-01T00:00:00Z"
        kernels = [
            {"execution_state": "idle", "last_activity": "2023-01-01T00:00:00Z"},
            {"execution_state": "idle", "last_activity": "2023-02-01T00:00:00Z"},
        ]
        culler.update_last_activity_from_kernels(meta, kernels, clock)
        # both kernel times predate the annotation: no backwards move
        assert meta.annotations[C.LAST_ACTIVITY_ANNOTATION] == "2023-06-01T00:00:00Z"
        kernels[1]["last_activity"] = "2023-07-01T00:00:00Z"
        culler.update_last_activity_from_kernels(meta, kernels, clock)
        assert meta.annotations[C.LAST_ACTIVITY_ANNOTATION] == "2023-07-01T00:00:00Z"

    def test_fractional_second_timestamps_parse(self):
        """Real Jupyter reports fractional seconds; they must advance the
        annotation (regression: strict %S parse silently dropped them)."""
        clock = FakeClock()
        meta = ObjectMeta()
        meta.annotations[C.LAST_ACTIVITY_ANNOTATION] = "2023-06-01T00:00:00Z"
        kernels = [{"execution_state": "idle",
                    "last_activity": "2023-07-29T10:00:00.533016Z"}]
        culler.update_last_activity_from_kernels(meta, kernels, clock)
        assert meta.annotations[C.LAST_ACTIVITY_ANNOTATION] == (
            "2023-07-29T10:00:00.533016Z"
        )

    def test_unparsable_timestamp_ignored(self):
        clock = FakeClock()
        meta = ObjectMeta()
        meta.annotations[C.LAST_ACTIVITY_ANNOTATION] = "2023-06-01T00:00:00Z"
        kernels = [{"execution_state": "idle", "last_activity": "not-a-time"}]
        culler.update_last_activity_from_kernels(meta, kernels, clock)
        assert meta.annotations[C.LAST_ACTIVITY_ANNOTATION] == "2023-06-01T00:00:00Z"


@pytest.fixture()
def env():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("n1", allocatable={"cpu": "64", "memory": "256Gi"})
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
    clock = FakeClock()
    mgr = Manager(api, clock=clock)
    metrics = NotebookMetrics(api)
    jupyter = FakeJupyterState()
    cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                     idleness_check_period_min=1)
    setup_core_controllers(mgr, cfg, metrics)
    setup_culling(mgr, cfg, jupyter, metrics)
    return api, mgr, clock, jupyter, metrics


def idle_kernel(ts="2023-01-01T00:00:00Z"):
    return {"id": "k1", "name": "python3", "last_activity": ts,
            "execution_state": "idle", "connections": 0}


class TestCullingFlow:
    def test_idle_notebook_culled_and_metrics(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("nb", "u1").obj)
        mgr.run_until_idle()
        jupyter.set_kernels("u1", "nb", [idle_kernel()])
        # annotations initialized on first pass
        nb = api.get("Notebook", "u1", "nb")
        assert C.LAST_ACTIVITY_ANNOTATION in nb.metadata.annotations
        # not yet idle: advance 30 min
        mgr.advance(30 * 60)
        nb = api.get("Notebook", "u1", "nb")
        assert not culler.stop_annotation_is_set(nb.metadata)
        # push past the 60-min idle threshold
        mgr.advance(35 * 60)
        nb = api.get("Notebook", "u1", "nb")
        assert culler.stop_annotation_is_set(nb.metadata)
        # notebook controller saw it: replicas 0, pod gone
        assert api.get("StatefulSet", "u1", "nb").spec["replicas"] == 0
        assert api.try_get("Pod", "u1", "nb-0") is None
        assert metrics.culling.value("u1", "nb") == 1
        # activity annotations removed once stopping
        mgr.run_until_idle()
        nb = api.get("Notebook", "u1", "nb")
        assert C.LAST_ACTIVITY_ANNOTATION not in nb.metadata.annotations

    def test_busy_kernel_prevents_cull(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("nb", "u1").obj)
        mgr.run_until_idle()
        busy = dict(idle_kernel(), execution_state="busy")
        jupyter.set_kernels("u1", "nb", [busy])
        for _ in range(5):
            mgr.advance(30 * 60)
        nb = api.get("Notebook", "u1", "nb")
        assert not culler.stop_annotation_is_set(nb.metadata)
        assert api.get("StatefulSet", "u1", "nb").spec["replicas"] == 1

    def test_uncull_reinitializes(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("nb", "u1").obj)
        mgr.run_until_idle()
        jupyter.set_kernels("u1", "nb", [idle_kernel()])
        mgr.advance(61 * 60)
        assert culler.stop_annotation_is_set(api.get("Notebook", "u1", "nb").metadata)
        # dashboard un-culls by removing the annotation
        def unstop():
            nb = api.get("Notebook", "u1", "nb")
            culler.remove_stop_annotation(nb.metadata)
            api.update(nb)
        from kubeflow_tpu.kube import retry_on_conflict
        retry_on_conflict(unstop)
        mgr.run_until_idle()
        assert api.get("StatefulSet", "u1", "nb").spec["replicas"] == 1
        assert api.get("Pod", "u1", "nb-0").body["status"]["phase"] == "Running"

    def test_unreachable_jupyter_does_not_cull_prematurely(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("nb", "u1").obj)
        mgr.run_until_idle()
        # jupyter returns None (unreachable): last-activity stays at init time,
        # so the notebook still culls after the idle window — matching the
        # reference (probe failure doesn't block culling)
        mgr.advance(61 * 60)
        nb = api.get("Notebook", "u1", "nb")
        assert culler.stop_annotation_is_set(nb.metadata)


class TestSliceAtomicCulling:
    def test_tpu_notebook_culled_whole_slice(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        assert len(api.list("Pod", namespace="u1")) == 4
        jupyter.set_kernels("u1", "tnb", [idle_kernel()])
        mgr.advance(61 * 60)
        # all four workers gone atomically
        assert api.list("Pod", namespace="u1") == []
        assert api.get("Notebook", "u1", "tnb").status["sliceHealth"] == "Stopped"

    def test_checkpoint_before_cull_handshake(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api)
        jupyter = FakeJupyterState()
        cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                         idleness_check_period_min=1, checkpoint_before_cull=True)
        setup_core_controllers(mgr, cfg, metrics)
        setup_culling(mgr, cfg, jupyter, metrics)
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        jupyter.set_kernels("u1", "tnb", [idle_kernel()])
        mgr.advance(61 * 60)
        nb = api.get("Notebook", "u1", "tnb")
        # first idle verdict: checkpoint requested, NOT yet stopped
        assert C.ANNOTATION_CHECKPOINT_REQUESTED in nb.metadata.annotations
        assert not culler.stop_annotation_is_set(nb.metadata)
        assert len(api.list("Pod", namespace="u1")) == 4
        # runtime acks the checkpoint -> culled on next pass
        nb.metadata.annotations[CHECKPOINT_COMPLETE_ANNOTATION] = "true"
        api.update(nb)
        mgr.advance(61)
        nb = api.get("Notebook", "u1", "tnb")
        assert culler.stop_annotation_is_set(nb.metadata)
        assert api.list("Pod", namespace="u1") == []

    def test_stale_checkpoint_state_reset_on_activity(self):
        """A stale checkpoint-complete from a previous cycle, or a stale
        request, must not bypass the next grace window."""
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                         idleness_check_period_min=1, checkpoint_before_cull=True)
        metrics = NotebookMetrics(api)
        jupyter = FakeJupyterState()
        setup_core_controllers(mgr, cfg, metrics)
        setup_culling(mgr, cfg, jupyter, metrics)
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        jupyter.set_kernels("u1", "tnb", [idle_kernel()])
        mgr.advance(61 * 60)  # idle -> checkpoint requested
        assert C.ANNOTATION_CHECKPOINT_REQUESTED in api.get(
            "Notebook", "u1", "tnb").metadata.annotations
        # user comes back: busy kernel resets the handshake
        jupyter.set_kernels(
            "u1", "tnb", [dict(idle_kernel(), execution_state="busy")])
        mgr.advance(2 * 60)
        anns = api.get("Notebook", "u1", "tnb").metadata.annotations
        assert C.ANNOTATION_CHECKPOINT_REQUESTED not in anns
        assert not culler.stop_annotation_is_set(
            api.get("Notebook", "u1", "tnb").metadata)

    def _signal_env(self, tmp_path):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                         idleness_check_period_min=1,
                         checkpoint_before_cull=True,
                         checkpoint_signal_root=str(tmp_path / "signals"))
        metrics = NotebookMetrics(api)
        jupyter = FakeJupyterState()
        setup_core_controllers(mgr, cfg, metrics)
        setup_culling(mgr, cfg, jupyter, metrics)
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        jupyter.set_kernels("u1", "tnb", [idle_kernel()])
        return api, mgr, clock, metrics, tmp_path / "signals" / "u1" / "tnb"

    def test_cull_signal_file_written_and_ack_honored(self, tmp_path):
        """Satellite: the cull path drives the ACTUAL CullSignalWatcher
        transport — the culler writes the request file, the in-pod
        checkpoint_on_cull hook fires off it, and the ack file (not just
        the annotation) releases the cull, all on the FakeClock."""
        from kubeflow_tpu.runtime.checkpoint import (
            CheckpointManager,
            CullSignalWatcher,
            checkpoint_on_cull,
        )

        api, mgr, clock, metrics, sig_dir = self._signal_env(tmp_path)
        mgr.advance(61 * 60)  # idle verdict -> request written, cull held
        nb = api.get("Notebook", "u1", "tnb")
        assert C.ANNOTATION_CHECKPOINT_REQUESTED in nb.metadata.annotations
        assert not culler.stop_annotation_is_set(nb.metadata)
        assert (sig_dir / "checkpoint-requested").read_text() == "true"

        # the runtime side: the per-step hook sees the request, saves, acks
        ckpt = CheckpointManager(str(tmp_path / "ckpt"), backend="local")
        hook = checkpoint_on_cull(ckpt, CullSignalWatcher(str(sig_dir)))
        assert hook(7, {"w": [1.0, 2.0]}) is True
        assert ckpt.latest_step() == 7
        assert (sig_dir / "checkpoint-complete").exists()

        # next culling pass: ack honored -> stop annotation lands, slice
        # transitions toward Stopping/Stopped, signal files retired
        mgr.advance(61)
        nb = api.get("Notebook", "u1", "tnb")
        assert culler.stop_annotation_is_set(nb.metadata)
        assert api.list("Pod", namespace="u1") == []
        assert nb.body["status"]["sliceHealth"] in ("Stopping", "Stopped")
        assert not (sig_dir / "checkpoint-requested").exists()
        assert not (sig_dir / "checkpoint-complete").exists()
        assert metrics.checkpoint_snapshots.value("u1", "cull") == 1

    def test_cull_signal_timeout_without_ack(self, tmp_path):
        """No ack ever arrives (runtime wedged): the grace window — one
        idleness check period — expires and the cull proceeds anyway."""
        api, mgr, clock, metrics, sig_dir = self._signal_env(tmp_path)
        mgr.advance(61 * 60)
        assert (sig_dir / "checkpoint-requested").exists()
        assert not culler.stop_annotation_is_set(
            api.get("Notebook", "u1", "tnb").metadata)
        mgr.advance(2 * 60)  # grace expired, still no ack file
        nb = api.get("Notebook", "u1", "tnb")
        assert culler.stop_annotation_is_set(nb.metadata)
        assert metrics.checkpoint_snapshots.value("u1", "cull") == 0

    def test_activity_resumption_clears_signal_files(self, tmp_path):
        api, mgr, clock, metrics, sig_dir = self._signal_env(tmp_path)
        mgr.advance(61 * 60)
        assert (sig_dir / "checkpoint-requested").exists()
        # the user comes back before the grace expires: bump the
        # last-activity annotation the culler trusts
        from kubeflow_tpu.kube import retry_on_conflict

        def touch():
            nb = api.get("Notebook", "u1", "tnb")
            nb.metadata.annotations[C.LAST_ACTIVITY_ANNOTATION] = \
                clock.now_iso()
            api.update(nb)

        retry_on_conflict(touch)
        mgr.advance(2 * 60)
        nb = api.get("Notebook", "u1", "tnb")
        assert not culler.stop_annotation_is_set(nb.metadata)
        assert C.ANNOTATION_CHECKPOINT_REQUESTED not in \
            nb.metadata.annotations
        assert not (sig_dir / "checkpoint-requested").exists()

    def test_checkpoint_grace_expires(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                         idleness_check_period_min=1, checkpoint_before_cull=True)
        metrics = NotebookMetrics(api)
        jupyter = FakeJupyterState()
        setup_core_controllers(mgr, cfg, metrics)
        setup_culling(mgr, cfg, jupyter, metrics)
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        jupyter.set_kernels("u1", "tnb", [idle_kernel()])
        mgr.advance(61 * 60)
        assert not culler.stop_annotation_is_set(
            api.get("Notebook", "u1", "tnb").metadata
        )
        # no ack; grace (= one check period) passes -> culled anyway
        mgr.advance(2 * 60)
        assert culler.stop_annotation_is_set(
            api.get("Notebook", "u1", "tnb").metadata
        )


class TestCullingRecoveryPrecedence:
    """Satellite regression (ISSUE 4): the culler and the self-healing
    engine can race on the same pods — a notebook that is being stopped
    (stop annotation set, slice_health Stopping/Stopped) must NEVER be
    'recovered', or the cull and the recovery fight pod-for-pod."""

    def test_stopping_notebook_is_never_recovered(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        api.clear_audit_log()
        nb = api.get("Notebook", "u1", "tnb")
        culler.set_stop_annotation(nb.metadata, clock)
        api.update(nb)
        mgr.run_until_idle()
        assert api.list("Pod", namespace="u1") == []
        status = api.get("Notebook", "u1", "tnb").body["status"]
        assert status["sliceHealth"] == "Stopped"
        # no recovery fired: no audited pod deletes (the scale-to-zero
        # deletions are the fake kubelet's, which is not audited), no
        # restart metric, no SliceRecovery event, no bookkeeping
        assert api.audit_log(verb="delete", kind="Pod") == []
        assert "SliceRecovery" not in [
            e.body.get("reason") for e in api.list("Event", namespace="u1")]
        assert "sliceRecovery" not in status

    def test_failed_worker_plus_stop_annotation_parks_cleanly(self, env):
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        # grab the fake cluster the fixture built: fail a worker without
        # letting the manager react, then stop the notebook — the failed
        # pod must be culled away, never slice-restarted
        cluster = env_cluster(api)
        cluster.fail_pod("u1", "tnb-2")
        nb = api.get("Notebook", "u1", "tnb")
        culler.set_stop_annotation(nb.metadata, clock)
        api.update(nb)
        api.clear_audit_log()
        mgr.run_until_idle()
        assert api.list("Pod", namespace="u1") == []
        status = api.get("Notebook", "u1", "tnb").body["status"]
        assert status["sliceHealth"] == "Stopped"
        assert api.audit_log(verb="delete", kind="Pod") == []
        assert metrics.slice_restarts.value("u1", "pod-failed") == 0.0

    def test_stale_bookkeeping_cleared_once_stopped(self, env):
        """A notebook culled mid-recovery drops its bookkeeping when it
        parks: an un-culled notebook starts with a fresh budget."""
        api, mgr, clock, jupyter, metrics = env
        api.create(Notebook.new("tnb", "u1", tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()
        cluster = env_cluster(api)
        cluster.fail_pod("u1", "tnb-1")
        mgr.run_until_idle()  # self-healing restarts the slice once
        status = api.get("Notebook", "u1", "tnb").body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert status.get("sliceRecovery"), "expected live bookkeeping"
        nb = api.get("Notebook", "u1", "tnb")
        culler.set_stop_annotation(nb.metadata, clock)
        api.update(nb)
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "tnb").body["status"]
        assert status["sliceHealth"] == "Stopped"
        assert "sliceRecovery" not in status


def env_cluster(api) -> FakeCluster:
    """The env fixture's FakeCluster is reachable through the ApiServer's
    watcher list — the fixture does not return it."""
    for w in api._watchers:  # noqa: SLF001 — test-only introspection
        owner = getattr(w, "__self__", None)
        if isinstance(owner, FakeCluster):
            return owner
    raise AssertionError("no FakeCluster attached to this ApiServer")


class TestCullingDisabled:
    def test_setup_returns_none_when_disabled(self):
        mgr = Manager(ApiServer(), clock=FakeClock())
        assert setup_culling(mgr, CoreConfig(enable_culling=False)) is None
