"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context path (SURVEY.md §5 "Long-context/sequence parallelism"): the
sequence dimension is sharded across the mesh's "sequence" axis; each device
holds a [B, S/n, H, D] block of q/k/v.  K/V blocks rotate around the ICI
ring with `lax.ppermute` while each device folds every visiting block into a
numerically-stable online softmax (flash-attention style m/l accumulators) —
full attention without ever materializing [S, S] or gathering K/V.

Compute/communication overlap is XLA's job: the ppermute for step i+1 is
independent of step i's einsum, and latency hiding on TPU comes from the
async collective scheduler.  Causality is enforced with GLOBAL POSITION
VECTORS that ride the ring: each shard's kv-position block rotates with its
k/v block, so the causal mask is a pure input-data comparison — no
`axis_index` anywhere in the mask.  That keeps the mask chains
input-dependent, which matters under composition: input-independent
`axis_index` chains get hoisted out of the manual region as zero-operand
manual computations, and when ring nests inside the pipeline engine's
partially-manual shard_map, sdy propagation assigns those hoisted
computations inconsistent shardings (MLIR verifier failure with
check_vma=True on jax 0.9).  Position vectors as real operands also make
packed/shifted sequences work unchanged.  Fully-masked blocks still
traverse the ring (uniform control flow keeps the collective schedule
identical on every shard).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .attention import _repeat_kv


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    positions: Optional[jax.Array],
    axis_name: str,
    causal: bool,
    softmax_scale: Optional[float],
) -> jax.Array:
    """Per-shard body (runs under shard_map).  q/k/v: [B, S_blk, H, D];
    positions: [B, S_blk] global token positions of this shard's block
    (required when causal)."""
    n = jax.lax.axis_size(axis_name)
    batch, q_len, num_heads, head_dim = q.shape
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    k = _repeat_kv(k, num_heads)
    v = _repeat_kv(v, num_heads)

    # the accumulators join a carry with device-varying k/v blocks; pcast
    # to='varying' marks the zero inits as varying over the same manual axes
    # as q so the loop carry is VMA-consistent (check_vma=True catches the
    # unreduced-cotangent bugs that silently broke nesting under the
    # pipeline axis)
    vma = tuple(jax.typeof(q).vma)
    out = jax.lax.pcast(
        jnp.zeros((batch, num_heads, q_len, head_dim), jnp.float32), vma,
        to="varying")
    row_max = jax.lax.pcast(
        jnp.full((batch, num_heads, q_len), -jnp.inf, jnp.float32), vma,
        to="varying")
    row_sum = jax.lax.pcast(
        jnp.zeros((batch, num_heads, q_len), jnp.float32), vma,
        to="varying")
    perm = [(i, (i + 1) % n) for i in range(n)]

    if causal and positions is None:
        raise ValueError("causal ring attention requires positions")

    def step(i, carry):
        # kv positions (causal only) rotate around the ring WITH their k/v
        # block, so the mask is a pure input-data comparison
        out, row_max, row_sum, k_blk, v_blk, *kv_pos = carry
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal:
            bias = jnp.where(
                positions[:, :, None] >= kv_pos[0][:, None, :], 0.0, -jnp.inf
            ).astype(jnp.float32)
            scores = scores + bias[:, None, :, :]
        blk_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        # fully-masked rows keep -inf max; exp(-inf - -inf) guards below
        correction = jnp.exp(row_max - new_max)
        correction = jnp.where(jnp.isfinite(row_max), correction, 0.0)
        probs = jnp.exp(scores - new_max[..., None])
        probs = jnp.where(jnp.isfinite(scores), probs, 0.0)
        out = out * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd",
            probs,
            v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
        row_max = new_max
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kv_pos = [jax.lax.ppermute(p, axis_name, perm) for p in kv_pos]
        return (out, row_max, row_sum, k_blk, v_blk, *kv_pos)

    init = (out, row_max, row_sum, k, v) + (
        (positions,) if causal else ())
    out, row_max, row_sum, *_ = jax.lax.fori_loop(0, n, step, init)
    out = out / jnp.maximum(row_sum, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _shard_mapped(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map against the CONTEXT mesh when already inside a
    (partially-)manual shard_map — the pipeline engine's stage body —
    so the same axes compose; the concrete mesh otherwise."""
    context = jax.sharding.get_abstract_mesh()
    return jax.shard_map(
        fn,
        mesh=mesh if context.empty else context,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=True,
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sequence",
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Sequence-parallel exact attention.  Inputs [B, S, H, D] with S
    sharded over `axis_name`; composes with batch sharding over
    `batch_axes` and head (tensor) sharding over `head_axis`.  positions
    [B, S] are the global token positions (default arange) — they enter the
    shard_map as data and their kv copy rotates with the k/v blocks.

    Differentiation is a custom VJP whose backward runs `jax.vjp` of the
    per-shard body INSIDE a fresh shard_map region (one forward recompute
    per backward — the framework's full-remat default does that anyway).
    Letting JAX transpose through the shard_map instead breaks when ring
    nests inside the pipeline engine's partially-manual region: the
    transpose machinery closure-captures residuals across the nested
    manual_computation boundary and sdy propagation assigns them
    inconsistent shardings (an MLIR verifier failure with check_vma=True
    on jax 0.9).  With the VJP self-contained, both directions are single
    manual regions and check_vma=True holds everywhere.
    """
    spec = P(batch_axes, axis_name, head_axis, None)
    pos_spec = P(batch_axes, axis_name)
    if causal and positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(q.shape[1], dtype=jnp.int32), q.shape[:2])
    if positions is None:
        positions = jnp.zeros(q.shape[:2], jnp.int32)

    def local_fwd(q_, k_, v_, pos_):
        return _ring_attention_local(q_, k_, v_, pos_, axis_name, causal,
                                     softmax_scale)

    @jax.custom_vjp
    def ring(q, k, v, pos):
        return _shard_mapped(
            local_fwd, mesh, (spec,) * 3 + (pos_spec,), spec)(q, k, v, pos)

    def ring_fwd(q, k, v, pos):
        return ring(q, k, v, pos), (q, k, v, pos)

    def ring_bwd(res, dout):
        q, k, v, pos = res

        def local_bwd(q_, k_, v_, pos_, d_):
            _, vjp = jax.vjp(
                lambda a, b, c: local_fwd(a, b, c, pos_), q_, k_, v_)
            return vjp(d_)

        dq, dk, dv = _shard_mapped(
            local_bwd, mesh, (spec,) * 3 + (pos_spec, spec),
            (spec,) * 3)(q, k, v, pos, dout)
        return dq, dk, dv, None

    ring.defvjp(ring_fwd, ring_bwd)
    return ring(q, k, v, positions)
