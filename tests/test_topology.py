"""TPU topology math tests."""

import pytest

from kubeflow_tpu.tpu.topology import ACCELERATORS, TopologyError, resolve


class TestResolve:
    @pytest.mark.parametrize(
        "acc,topo,chips,hosts,per_host",
        [
            # v5e (2D): single host up to 8 chips, then 4 chips/host
            ("v5e", "1x1", 1, 1, 1),
            ("v5e", "2x2", 4, 1, 4),
            ("v5e", "2x4", 8, 1, 8),
            ("v5e", "4x4", 16, 4, 4),       # BASELINE config #4 (v5e-16)
            ("v5e", "4x8", 32, 8, 4),
            ("v5e", "8x8", 64, 16, 4),
            ("v5e", "16x16", 256, 64, 4),
            # v6e mirrors v5e shapes
            ("v6e", "4x4", 16, 4, 4),
            # v4/v5p (3D): 4 chips per host
            ("v4", "2x2x1", 4, 1, 4),
            ("v4", "2x2x4", 16, 4, 4),
            ("v5p", "2x2x1", 4, 1, 4),
            ("v5p", "2x2x2", 8, 2, 4),
            ("v5p", "4x4x8", 128, 32, 4),   # BASELINE config #5 (v5p-128)
        ],
    )
    def test_known_topologies(self, acc, topo, chips, hosts, per_host):
        shape = resolve(acc, topo)
        assert shape.chips == chips
        assert shape.num_hosts == hosts
        assert shape.chips_per_host == per_host

    def test_unknown_accelerator(self):
        with pytest.raises(TopologyError, match="unknown accelerator"):
            resolve("v99", "2x2")

    def test_wrong_dims(self):
        with pytest.raises(TopologyError, match="dimensions"):
            resolve("v5e", "2x2x2")  # v5e is 2D
        with pytest.raises(TopologyError, match="dimensions"):
            resolve("v5p", "4x4")  # v5p is 3D

    def test_garbage(self):
        with pytest.raises(TopologyError):
            resolve("v5e", "axb")
        with pytest.raises(TopologyError):
            resolve("v5e", "0x4")

    def test_peak_flops_scales_with_chips(self):
        shape = resolve("v5e", "4x4")
        assert shape.bf16_peak_tflops == 16 * ACCELERATORS["v5e"].bf16_peak_tflops
