"""Notebook API type tests: versions, conversion, validation (reference
api/v1/notebook_conversion.go:25-69, field-identical version set)."""

import pytest

from kubeflow_tpu.api.types import HUB_VERSION, Notebook, TPUSpec, VERSIONS
from kubeflow_tpu.kube import InvalidError


class TestConversion:
    def test_roundtrip_all_versions_lossless(self):
        nb = Notebook.new(
            "nb", "ns", tpu=TPUSpec("v5e", "4x4", slices=2),
            pod_spec={"containers": [{"name": "nb", "image": "img"}]},
            version="v1",
        )
        for v in VERSIONS:
            converted = nb.convert_to(v)
            assert converted.version == v
            assert converted.obj.body == nb.obj.body
            back = converted.convert_to("v1")
            assert back.obj.to_dict() == nb.obj.to_dict()

    def test_unknown_version_rejected(self):
        nb = Notebook.new("nb", "ns")
        with pytest.raises(InvalidError):
            nb.convert_to("v2")

    def test_hub_is_v1beta1(self):
        assert HUB_VERSION == "v1beta1"


class TestValidation:
    def test_empty_containers_rejected(self):
        nb = Notebook.new("nb", "ns", pod_spec={"containers": []})
        with pytest.raises(InvalidError):
            nb.validate()

    def test_tpu_spec_validated(self):
        nb = Notebook.new("nb", "ns", tpu=TPUSpec("v5e", "9x9x9"))
        with pytest.raises(InvalidError):
            nb.validate()

    def test_valid_tpu_shape_exposed(self):
        nb = Notebook.new("nb", "ns", tpu=TPUSpec("v5e", "4x4"))
        nb.validate()
        assert nb.tpu.shape.num_hosts == 4

    def test_schema_enforced_at_apiserver(self):
        from kubeflow_tpu.kube import AdmissionDenied, ApiServer
        from kubeflow_tpu.api.validation import install_notebook_schema

        api = ApiServer()
        install_notebook_schema(api)
        with pytest.raises(AdmissionDenied, match="containers"):
            api.create(Notebook.new("bad", "ns", pod_spec={"containers": []}).obj)
        with pytest.raises(AdmissionDenied, match="not served"):
            bad = Notebook.new("bad", "ns")
            bad.obj.api_version = "kubeflow.org/v9"
            api.create(bad.obj)
        api.create(Notebook.new("good", "ns", tpu=TPUSpec("v5e", "2x2")).obj)
