"""Gateway API routing plane: HTTPRoutes + ReferenceGrants.

Port of odh notebook_route.go and notebook_referencegrant.go semantics:
HTTPRoutes live in the *central* (controller) namespace — cross-namespace, so
no owner references; cleanup rides finalizers on the Notebook — and a single
shared ReferenceGrant per user namespace authorizes the central-ns routes to
reference local Services (notebook_route.go:51-132, 144-325;
notebook_referencegrant.go:39-184).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..api.types import Notebook
from ..kube import ApiServer, KubeObject, NotFoundError, ObjectMeta, retry_on_conflict
from . import constants as C


def _route_labels(nb: Notebook) -> dict[str, str]:
    return {
        C.NOTEBOOK_NAME_LABEL: nb.name,
        C.NOTEBOOK_NAMESPACE_LABEL: nb.namespace,
    }


def new_notebook_httproute(
    nb: Notebook,
    central_namespace: str,
    gateway_name: str,
    gateway_namespace: str,
) -> KubeObject:
    """Desired HTTPRoute `nb-{ns}-{name}` in the central namespace: parentRef
    the platform Gateway, path /notebook/{ns}/{name}, cross-namespace
    backendRef to the notebook Service :8888 (notebook_route.go:51-132)."""
    name = f"nb-{nb.namespace}-{nb.name}"
    if len(name) > C.HTTPROUTE_NAME_MAX_LEN:
        # >63-char names fall back to generateName with truncated components
        # (notebook_route.go:68-79)
        prefix = f"nb-{nb.namespace[:10]}-{nb.name[:10]}-"
        meta = ObjectMeta(
            generate_name=prefix, namespace=central_namespace, labels=_route_labels(nb)
        )
    else:
        meta = ObjectMeta(
            name=name, namespace=central_namespace, labels=_route_labels(nb)
        )
    return KubeObject(
        api_version="gateway.networking.k8s.io/v1",
        kind="HTTPRoute",
        metadata=meta,
        body={
            "spec": {
                "parentRefs": [
                    {"name": gateway_name, "namespace": gateway_namespace}
                ],
                "rules": [
                    {
                        "matches": [
                            {
                                "path": {
                                    "type": "PathPrefix",
                                    "value": f"/notebook/{nb.namespace}/{nb.name}",
                                }
                            }
                        ],
                        "backendRefs": [
                            {
                                "name": nb.name,
                                "namespace": nb.namespace,
                                "port": C.NOTEBOOK_PORT,
                            }
                        ],
                    }
                ],
            }
        },
    )


def new_kube_rbac_proxy_httproute(
    nb: Notebook,
    central_namespace: str,
    gateway_name: str,
    gateway_namespace: str,
) -> KubeObject:
    """Auth-mode variant: same route shape but the backend is the per-notebook
    kube-rbac-proxy Service :8443 (notebook_kube_rbac_auth.go:162-177)."""
    route = new_notebook_httproute(nb, central_namespace, gateway_name, gateway_namespace)
    backend = route.spec["rules"][0]["backendRefs"][0]
    backend["name"] = nb.name + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX
    backend["port"] = C.KUBE_RBAC_PROXY_PORT
    return route


def list_notebook_httproutes(
    api: ApiServer, nb: Notebook, central_namespace: str
) -> list[KubeObject]:
    """Central-namespace routes of this notebook, matched by labels — cross-ns
    objects cannot carry owner references (notebook_route.go:157-165)."""
    return api.list(
        "HTTPRoute", namespace=central_namespace, label_selector=_route_labels(nb)
    )


def reconcile_httproute(
    api: ApiServer,
    nb: Notebook,
    central_namespace: str,
    gateway_name: str,
    gateway_namespace: str,
    new_route: Optional[Callable[..., KubeObject]] = None,
) -> KubeObject:
    """Create-or-update by label match (notebook_route.go:144-219)."""
    new_route = new_route or new_notebook_httproute
    desired = new_route(nb, central_namespace, gateway_name, gateway_namespace)
    existing = list_notebook_httproutes(api, nb, central_namespace)
    if len(existing) > 1:
        raise RuntimeError(
            f"multiple HTTPRoutes found for notebook {nb.namespace}/{nb.name}"
        )
    if not existing:
        return api.create(desired)
    found = existing[0]
    if (
        found.metadata.labels == desired.metadata.labels
        and found.body.get("spec") == desired.body.get("spec")
    ):
        return found

    def update() -> None:
        live = api.get("HTTPRoute", central_namespace, found.name)
        live.metadata.labels = dict(desired.metadata.labels)
        live.body["spec"] = desired.body.get("spec")
        api.update(live)

    retry_on_conflict(update)
    return api.get("HTTPRoute", central_namespace, found.name)


def delete_httproutes_for_notebook(
    api: ApiServer, nb: Notebook, central_namespace: str
) -> None:
    """Finalizer cleanup: delete every labeled route
    (notebook_route.go:230-266)."""
    for route in list_notebook_httproutes(api, nb, central_namespace):
        try:
            api.delete("HTTPRoute", central_namespace, route.name)
        except NotFoundError:
            pass


def ensure_conflicting_httproute_absent(
    api: ApiServer, nb: Notebook, central_namespace: str, is_auth_mode: bool
) -> None:
    """When auth mode flips, the other mode's route must go first — both
    claim the same path prefix (notebook_route.go:268-325)."""
    for route in list_notebook_httproutes(api, nb, central_namespace):
        rules = route.spec.get("rules") or []
        if not rules or not rules[0].get("backendRefs"):
            continue
        backend = rules[0]["backendRefs"][0]
        name, port = backend.get("name"), backend.get("port")
        is_proxy_route = (
            name == nb.name + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX
            or port == C.KUBE_RBAC_PROXY_PORT
        )
        is_regular_route = name == nb.name or port == C.NOTEBOOK_PORT
        if (is_auth_mode and is_regular_route and not is_proxy_route) or (
            not is_auth_mode and is_proxy_route
        ):
            try:
                api.delete("HTTPRoute", central_namespace, route.name)
            except NotFoundError:
                pass


# -- ReferenceGrant ------------------------------------------------------------


def new_reference_grant(namespace: str, central_namespace: str) -> KubeObject:
    """One shared grant per user namespace: central-ns HTTPRoutes -> local
    Services (notebook_referencegrant.go:39-69)."""
    return KubeObject(
        api_version="gateway.networking.k8s.io/v1beta1",
        kind="ReferenceGrant",
        metadata=ObjectMeta(
            name=C.REFERENCEGRANT_NAME,
            namespace=namespace,
            labels={"app.kubernetes.io/managed-by": "odh-notebook-controller"},
        ),
        body={
            "spec": {
                "from": [
                    {
                        "group": "gateway.networking.k8s.io",
                        "kind": "HTTPRoute",
                        "namespace": central_namespace,
                    }
                ],
                "to": [{"group": "", "kind": "Service"}],
            }
        },
    )


def reconcile_reference_grant(
    api: ApiServer, nb: Notebook, central_namespace: str
) -> KubeObject:
    """Create-if-missing, fix-if-drifted (notebook_referencegrant.go:81-126)."""
    desired = new_reference_grant(nb.namespace, central_namespace)
    found = api.try_get("ReferenceGrant", nb.namespace, C.REFERENCEGRANT_NAME)
    if found is None:
        return api.create(desired)
    if (
        found.metadata.labels == desired.metadata.labels
        and found.body.get("spec") == desired.body.get("spec")
    ):
        return found

    def update() -> None:
        live = api.get("ReferenceGrant", nb.namespace, C.REFERENCEGRANT_NAME)
        live.metadata.labels = dict(desired.metadata.labels)
        live.body["spec"] = desired.body.get("spec")
        api.update(live)

    retry_on_conflict(update)
    return api.get("ReferenceGrant", nb.namespace, C.REFERENCEGRANT_NAME)


def is_last_notebook_in_namespace(api: ApiServer, nb: Notebook) -> bool:
    """True when no *other* live notebook remains in the namespace
    (notebook_referencegrant.go:166-184)."""
    for other in api.list("Notebook", namespace=nb.namespace):
        if other.name == nb.name:
            continue
        if other.metadata.deletion_timestamp is None:
            return False
    return True


def delete_reference_grant_if_last_notebook(api: ApiServer, nb: Notebook) -> None:
    """The grant is shared; deleted only with the namespace's last notebook
    (notebook_referencegrant.go:130-162)."""
    if not is_last_notebook_in_namespace(api, nb):
        return
    try:
        api.delete("ReferenceGrant", nb.namespace, C.REFERENCEGRANT_NAME)
    except NotFoundError:
        pass
