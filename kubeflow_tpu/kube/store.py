"""In-memory API server: the substrate both controllers reconcile against.

This plays the role etcd + kube-apiserver play for the reference (its tests
spin a real apiserver via envtest,
components/notebook-controller/controllers/suite_test.go:50-110; we keep the
same semantics — optimistic concurrency on resourceVersion, admission chain in
the write path, finalizer-gated deletion, owner-reference garbage collection,
watch fan-out) in a deterministic, dependency-free form suitable for pytest
and for running the whole stack standalone.

Fleet-scale internals (the 10k-notebook convergence gate forced them):

  - **Sharded per kind.**  Each kind owns a shard — its own lock, object
    map, and bounded watch-history ring (`WATCH_HISTORY_SIZE` events per
    kind) — so 8+ workers converging Notebooks never serialize behind Pod
    churn, and a chatty kind cannot evict another kind's resume window.
  - **Filtered watch dispatch.**  `watch`/`subscribe` take `kinds=` and
    `namespace=` filters; dispatch goes through a per-kind subscriber
    index, so an event only ever touches interested watchers.  The
    `watch_dispatch_counts()` audit (exported as
    `apiserver_watch_dispatch_total{kind,result}`) proves the fan-out
    reduction: `skipped` counts the callbacks an unfiltered broadcast
    would have made but the index didn't.
  - **Copy-on-write reads.**  Committed objects are immutable — every
    write path replaces, never mutates, the stored object — so `list`
    returns the stored objects themselves with NO per-object deepcopy,
    and watch events carry one shared frozen object to every watcher.
    The contract: objects handed out by `list` (and by watch callbacks)
    are READ-ONLY; mutating one without going through a fresh `get()` +
    `update()` is a bug.  `get` still returns a private copy, so the
    universal mutate-then-update pattern keeps working unchanged.
  - **Apply fast path.**  A server-side apply whose manifest digest and
    target resourceVersion both match the previous apply by the same
    field manager short-circuits before any merge machinery runs — a
    GitOps loop re-applying unchanged config costs one dict lookup.
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
import os
import threading
from contextlib import ExitStack, contextmanager
from dataclasses import dataclass
from enum import Enum
from typing import Callable, Iterable, Optional

from collections import deque

from ..utils import invariants
from .errors import (
    AlreadyExistsError,
    ConflictError,
    ForbiddenError,
    GoneError,
    InvalidError,
    NotFoundError,
)
from .meta import KubeObject, copy_tree, new_uid, now_iso

DEFAULT_WATCH_HISTORY_SIZE = 2048


def _default_history_size() -> int:
    try:
        return max(1, int(os.environ.get("WATCH_HISTORY_SIZE", "")
                          or DEFAULT_WATCH_HISTORY_SIZE))
    except ValueError:
        return DEFAULT_WATCH_HISTORY_SIZE


class EventType(Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: EventType
    obj: KubeObject
    # pre-update state on MODIFIED events (None on ADDED/DELETED) — the
    # watch cache keeps it so selector-filtered watches can detect an
    # object editing into/out of the selected set (the apiserver's cacher
    # does the same to synthesize ADDED/DELETED transitions)
    prev: Optional[KubeObject] = None


class AdmissionDenied(ForbiddenError):
    """Raised by a validating admission hook to reject a write."""


@dataclass
class AuditRecord:
    """One observed top-level client WRITE (create/update/patch/delete).

    The audit log is the ground truth chaos tests assert invariants
    against — e.g. that the self-healing engine only ever issues
    whole-slice pod deletions, never partial-slice ones.  `ok` is False
    when the verb raised (an injected fault or a genuine API error): the
    client still *attempted* the write, which is what atomicity claims
    are about.  Internal re-entry (GC cascades, admission, the
    FakeCluster data plane, `fault_exempt` harness calls) is NOT audited:
    the log captures controller traffic at the client↔apiserver boundary
    only.  `name` is the name as the client sent it (empty for a
    generateName create); `rv` is the cluster resourceVersion after the
    verb, an ordering key across the log."""

    verb: str
    kind: str
    namespace: str
    name: str
    ok: bool = True
    error: str = ""
    rv: int = 0


@dataclass
class AdmissionHook:
    """Registered admission webhook (mutating or validating).

    The reference registers these on the apiserver via
    WebhookInstallOptions (odh suite_test.go:121-124); handlers receive the
    old and new object and either mutate (mutating) or raise AdmissionDenied
    (validating).  `operations` is a subset of {"CREATE", "UPDATE", "DELETE"}.
    """

    kinds: tuple[str, ...]
    handler: Callable[[str, Optional[KubeObject], KubeObject], Optional[KubeObject]]
    operations: tuple[str, ...] = ("CREATE", "UPDATE")
    mutating: bool = True
    name: str = ""


def match_labels(labels: dict[str, str], selector: Optional[dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class _KindShard:
    """Per-kind store partition: object map + watch-history ring under one
    lock, so writes to different kinds never contend."""

    __slots__ = ("lock", "objects", "history", "floor")

    def __init__(self, history_size: int, kind: str = "") -> None:
        # rank = kind: under INVARIANTS_STRICT the LockTracker enforces
        # that multi-shard acquisition (subscribe replay) follows the
        # documented sorted-by-kind order
        self.lock = invariants.tracked(
            threading.RLock(), "ApiServer.shard.lock", rank=kind)
        self.objects: dict[tuple[str, str], KubeObject] = {}
        self.history: deque[WatchEvent] = deque(maxlen=history_size)
        # resourceVersions <= the floor have been evicted from this kind's
        # history: a resume from below it cannot prove nothing was missed
        # for this kind -> 410
        self.floor = 0


@dataclass
class _WatchEntry:
    """One registered watcher with its delivery filter.  `kinds=None`
    means every kind (legacy unfiltered broadcast); namespace=None means
    every namespace."""

    fn: Callable[[WatchEvent], None]
    kinds: Optional[frozenset]
    namespace: Optional[str]


class ApiServer:
    """Thread-safe in-memory object store with k8s write-path semantics."""

    def __init__(self, history_size: Optional[int] = None) -> None:
        self.history_size = history_size if history_size is not None \
            else _default_history_size()
        # INVARIANTS_STRICT=1: commit-time deep-freeze + lock-order
        # tracking (utils.invariants); read once — the strict suites set
        # the env var before constructing the ApiServer
        self._strict = invariants.strict_enabled()
        # kind -> shard (object map + history ring, per-kind lock)
        self._shards: dict[str, _KindShard] = {}
        self._shards_lock = invariants.tracked(
            threading.RLock(), "ApiServer._shards_lock")
        # rv/name counters (globally ordered; own lock so a shard-lock
        # holder can allocate without touching other shards)
        self._rv_lock = invariants.tracked(
            threading.Lock(), "ApiServer._rv_lock")
        self._rv_counter = 0
        self._name_counter = 0
        # watcher registry + per-kind dispatch index.  Lock ordering:
        # _shards_lock > shard.lock (sorted by kind) > _watch_lock; the
        # rv/audit locks are leaves and never acquire anything.
        self._watch_lock = invariants.tracked(
            threading.RLock(), "ApiServer._watch_lock")
        self._watch_entries: list[_WatchEntry] = []
        self._kind_index: dict[str, list[_WatchEntry]] = {}
        self._unfiltered: list[_WatchEntry] = []
        # (kind, "delivered"|"skipped") -> count: the fan-out audit.
        # skipped = registered watchers an unfiltered broadcast would have
        # called for the event but the per-kind index did not.
        self._dispatch_counts: dict[tuple[str, str], int] = {}
        self._mutating: list[AdmissionHook] = []
        self._validating: list[AdmissionHook] = []
        # fault injection (kube.faults): a plan gates top-level verb entry;
        # re-entrant internals and watch-driven components run at depth > 0
        # and are exempt (thread-local so threaded managers stay correct)
        self._fault_plan = None
        self._fault_ctx = threading.local()
        # bounded audit trail of top-level client writes (AuditRecord);
        # shares the depth gate with fault injection, so only controller
        # traffic is recorded — never the store's own re-entry
        self._audit_lock = invariants.tracked(
            threading.Lock(), "ApiServer._audit_lock")
        self._audit_log: deque[AuditRecord] = deque(maxlen=8192)
        # per-(verb, kind) counters over ALL top-level client verbs, reads
        # included (the audit log keeps write detail; these stay O(verbs x
        # kinds) so a load test can budget total API traffic cheaply)
        self._verb_counts: dict[tuple[str, str], int] = {}
        # per-(verb, kind, namespace) counters — the tenant-attribution
        # feed (utils/metering.py delta-reads these); cluster-scoped
        # calls land under namespace ""
        self._tenant_verb_counts: dict[tuple[str, str, str], int] = {}
        # apply fast path: (kind, ns, name) -> field_manager ->
        # (manifest digest, resulting rv); see apply()
        self._apply_lock = invariants.tracked(
            threading.Lock(), "ApiServer._apply_lock")
        self._applied_digests: dict[
            tuple[str, str, str], dict[str, tuple[str, int]]] = {}

    # -- shards ---------------------------------------------------------------
    def _shard(self, kind: str) -> _KindShard:
        with self._shards_lock:
            shard = self._shards.get(kind)
            if shard is None:
                shard = self._shards[kind] = _KindShard(
                    self.history_size, kind)
            return shard

    # -- fault injection ------------------------------------------------------
    def install_fault_plan(self, plan) -> None:
        """Install a kube.faults.FaultPlan on the API surface.  Replaces any
        existing plan; None (or clear_fault_plan) removes it."""
        self._fault_plan = plan

    def clear_fault_plan(self) -> None:
        self._fault_plan = None

    @property
    def fault_plan(self):
        return self._fault_plan

    @contextmanager
    def fault_exempt(self):
        """Run a block immune to the installed fault plan — for test-harness
        setup/assertion calls and cluster-internal components (the faults
        model client<->apiserver failures, not the store's own integrity)."""
        depth = getattr(self._fault_ctx, "depth", 0)
        self._fault_ctx.depth = depth + 1
        try:
            yield
        finally:
            self._fault_ctx.depth = depth

    @contextmanager
    def _fault_scope(self, verb: str, kind: str, namespace: str = "",
                     name: str = ""):
        """Top-level verb gate: consult the fault plan once per outermost
        call (nested ApiServer re-entry — GC, patch retry loops, admission,
        watch fan-out — runs at depth > 0 and is exempt).  Yields optional
        directives for the verb body (e.g. {"stale": True})."""
        depth = getattr(self._fault_ctx, "depth", 0)
        self._fault_ctx.depth = depth + 1
        audited = depth == 0 and verb in ("create", "update", "patch",
                                          "delete")
        if depth == 0:
            with self._audit_lock:
                key = (verb, kind)
                self._verb_counts[key] = self._verb_counts.get(key, 0) + 1
                tkey = (verb, kind, namespace)
                self._tenant_verb_counts[tkey] = \
                    self._tenant_verb_counts.get(tkey, 0) + 1
        try:
            directives = None
            if depth == 0 and self._fault_plan is not None:
                # plan actions (watch drops -> resubscribe -> relist) run
                # inside this scope, so they cannot recursively re-fault
                directives = self._fault_plan.intercept(
                    self, verb, kind, namespace, name)
            yield directives
        except BaseException as err:
            if audited:
                with self._audit_lock:
                    self._audit_log.append(AuditRecord(
                        verb, kind, namespace, name, ok=False,
                        error=str(err), rv=self._rv_counter))
            raise
        else:
            if audited:
                with self._audit_lock:
                    self._audit_log.append(AuditRecord(
                        verb, kind, namespace, name, ok=True,
                        rv=self._rv_counter))
        finally:
            self._fault_ctx.depth = depth

    # -- audit trail ----------------------------------------------------------
    def audit_log(self, verb: Optional[str] = None,
                  kind: Optional[str] = None,
                  ok: Optional[bool] = None) -> list[AuditRecord]:
        """The recorded top-level client writes, oldest first, optionally
        filtered.  Chaos tests read this to prove client-side invariants
        (e.g. slice-atomicity of recovery restarts)."""
        with self._audit_lock:
            return [
                r for r in self._audit_log
                if (verb is None or r.verb == verb)
                and (kind is None or r.kind == kind)
                and (ok is None or r.ok == ok)
            ]

    def clear_audit_log(self) -> None:
        with self._audit_lock:
            self._audit_log.clear()

    def verb_counts(self) -> dict[tuple[str, str], int]:
        """Cumulative (verb, kind) -> count over every top-level client
        call, reads included.  The loadtest convergence benchmark budgets
        API traffic against this; `fault_exempt` harness calls and internal
        re-entry are never counted."""
        with self._audit_lock:
            return dict(self._verb_counts)

    def clear_verb_counts(self) -> None:
        with self._audit_lock:
            self._verb_counts.clear()

    def tenant_verb_counts(self) -> dict[tuple[str, str, str], int]:
        """Cumulative (verb, kind, namespace) -> count — verb_counts()
        partitioned by the owning tenant (cluster-scoped calls under
        namespace "").  The metering ledger delta-reads this snapshot to
        attribute apiserver traffic per tenant."""
        with self._audit_lock:
            return dict(self._tenant_verb_counts)

    def clear_tenant_verb_counts(self) -> None:
        with self._audit_lock:
            self._tenant_verb_counts.clear()

    # -- watch / admission registration --------------------------------------
    @property
    def _watchers(self) -> list[Callable[[WatchEvent], None]]:
        """Registered callbacks (test-only introspection surface; the
        registry itself lives in filtered _WatchEntry records)."""
        with self._watch_lock:
            return [e.fn for e in self._watch_entries]

    @staticmethod
    def _kindset(kinds) -> Optional[frozenset]:
        if kinds is None:
            return None
        return frozenset(kinds)

    def _register_entry(self, entry: _WatchEntry) -> None:
        # caller holds _watch_lock
        self._watch_entries.append(entry)
        if entry.kinds is None:
            self._unfiltered.append(entry)
        else:
            for k in entry.kinds:
                self._kind_index.setdefault(k, []).append(entry)

    def _deregister_entry(self, entry: _WatchEntry) -> None:
        # caller holds _watch_lock
        self._watch_entries.remove(entry)
        if entry.kinds is None:
            self._unfiltered.remove(entry)
        else:
            for k in entry.kinds:
                bucket = self._kind_index.get(k)
                if bucket is not None:
                    if entry in bucket:
                        bucket.remove(entry)
                    if not bucket:
                        del self._kind_index[k]

    def watch(self, fn: Callable[[WatchEvent], None],
              kinds: Optional[Iterable[str]] = None,
              namespace: Optional[str] = None) -> None:
        """Register a live watcher.  `kinds` restricts delivery to those
        kinds (None = every kind); `namespace` restricts to one namespace.
        Watch callbacks receive SHARED frozen objects — they must never
        mutate the event or anything it references."""
        entry = _WatchEntry(fn, self._kindset(kinds), namespace or None)
        with self._watch_lock:
            self._register_entry(entry)

    def unwatch(self, fn: Callable[[WatchEvent], None]) -> None:
        with self._watch_lock:
            for entry in list(self._watch_entries):
                if entry.fn is fn:
                    self._deregister_entry(entry)

    def update_watch_kinds(self, fn: Callable[[WatchEvent], None],
                           kinds: Optional[Iterable[str]]) -> None:
        """Re-filter an already-registered watcher (forward-only: past
        events of newly added kinds are not replayed — new consumers prime
        with list_with_rv, which is exactly what the informer cache does)."""
        kindset = self._kindset(kinds)
        with self._watch_lock:
            for entry in self._watch_entries:
                if entry.fn is fn:
                    self._deregister_entry(entry)
                    entry.kinds = kindset
                    self._register_entry(entry)
                    return

    def subscribe(self, fn: Callable[[WatchEvent], None],
                  since_rv: Optional[int] = None,
                  kinds: Optional[Iterable[str]] = None,
                  namespace: Optional[str] = None) -> None:
        """Register a watcher, first replaying history newer than `since_rv`
        atomically (no events can be missed between replay and live stream).
        since_rv=None starts live-only; raises GoneError when since_rv
        predates the retained window of ANY kind the watcher asked for —
        per-kind rings mean Pod churn can never evict a Notebook-only
        subscriber's resume window."""
        kindset = self._kindset(kinds)
        if since_rv is None:
            self.watch(fn, kinds=kinds, namespace=namespace)
            return
        entry = _WatchEntry(fn, kindset, namespace or None)
        with self._shards_lock:
            relevant = sorted(
                k for k in self._shards
                if kindset is None or k in kindset)
            with ExitStack() as stack:
                shards = []
                for k in relevant:
                    shard = self._shards[k]
                    stack.enter_context(shard.lock)
                    shards.append(shard)
                # a resume below any relevant eviction floor cannot prove
                # nothing was missed (events <= floor left that kind's
                # window — sliding eviction or a reset_watch_history
                # compaction)
                floor = max((s.floor for s in shards), default=0)
                if since_rv < floor:
                    raise GoneError(
                        f"resourceVersion {since_rv} is too old "
                        f"(history starts at {floor + 1})"
                    )
                replay: list[WatchEvent] = []
                for shard in shards:
                    for ev in shard.history:
                        if ev.obj.metadata.resource_version <= since_rv:
                            continue
                        if entry.namespace is not None and \
                                ev.obj.namespace != entry.namespace:
                            continue
                        replay.append(ev)
                # rv order across kinds: per-kind rings are merged back
                # into the global commit order
                replay.sort(key=lambda ev: ev.obj.metadata.resource_version)
                with self._watch_lock:
                    # prev rides along: resumed selector-filtered watches
                    # need it to synthesize edit-in/edit-out transitions
                    # that happened while they were away
                    for ev in replay:
                        fn(ev)
                    self._register_entry(entry)

    def drop_watch_connections(self) -> int:
        """Disconnect every RESUMABLE watcher (one with an
        `on_watch_dropped` method) — the analog of the apiserver closing
        client watch streams.  Plain callback watchers (the FakeCluster
        data plane, test listeners) stay connected: a stream drop models
        the client side of the watch, and a consumer with no resume
        protocol would just silently go deaf.  Returns how many dropped."""
        with self._watch_lock:
            dropped = [e for e in self._watch_entries
                       if hasattr(e.fn, "on_watch_dropped")]
            for e in dropped:
                self._deregister_entry(e)
        for e in dropped:
            e.fn.on_watch_dropped()
        return len(dropped)

    def reset_watch_history(self) -> None:
        """Evict the whole watch-resume window of every kind (etcd
        compaction): any subsequent resume from a pre-reset resourceVersion
        gets 410 Gone and must relist.  Each shard's floor rises to the
        compaction point under that shard's lock, so a concurrent filtered
        subscribe either completes against the pre-compaction window or
        sees the raised floor and 410s — an evicted rv is never silently
        skipped in a replay."""
        with self._shards_lock:
            shards = list(self._shards.values())
        with self._rv_lock:
            rv = self._rv_counter
        for shard in shards:
            with shard.lock:
                shard.history.clear()
                shard.floor = max(shard.floor, rv)

    def watch_dispatch_counts(self) -> dict[tuple[str, str], int]:
        """Cumulative (kind, "delivered"|"skipped") dispatch audit.
        delivered = callbacks actually invoked for events of the kind;
        skipped = callbacks an unfiltered broadcast would have invoked but
        the per-kind index did not.  Exported by core.metrics as
        apiserver_watch_dispatch_total."""
        with self._watch_lock:
            return dict(self._dispatch_counts)

    def _stale_of(self, kind: str, namespace: str,
                  name: str) -> Optional[KubeObject]:
        """The most recent PREVIOUS version of an object still in the watch
        history — what a lagging apiserver cache would serve."""
        shard = self._shard(kind)
        with shard.lock:
            for ev in reversed(shard.history):
                o = ev.obj
                if (o.namespace, o.name) == (namespace, name) \
                        and ev.prev is not None:
                    return ev.prev.deepcopy()
        return None

    @property
    def resource_version(self) -> int:
        with self._rv_lock:
            return self._rv_counter

    def register_admission(self, hook: AdmissionHook) -> None:
        with self._watch_lock:
            (self._mutating if hook.mutating else self._validating).append(hook)

    def _notify(self, ev: WatchEvent) -> None:
        """Append to the kind's history ring and dispatch to interested
        watchers only.  Ring append + watcher snapshot are atomic with a
        subscribe()'s replay-then-register (both hold shard.lock then
        _watch_lock), so an event is delivered to a resuming watcher
        exactly once — via replay or live, never both.  The event carries
        ONE shared frozen object: no per-watcher deepcopy; callbacks must
        only read it, and may only enqueue or re-enter this ApiServer."""
        kind = ev.obj.kind
        # model-checker schedule point: a commit becoming visible is where
        # optimistic-concurrency races decide (testing/interleave.py)
        invariants.yield_point(
            "store.commit",
            (ev.type.value, kind, ev.obj.namespace, ev.obj.name))
        ev.obj.frozen = True
        if ev.prev is not None:
            ev.prev.frozen = True
        if self._strict:
            # mutation-trapping wrappers over the shared trees: any
            # escaped write raises at the mutation site (utils.invariants)
            invariants.deep_freeze(ev.obj)
            if ev.prev is not None:
                invariants.deep_freeze(ev.prev)
        shard = self._shard(kind)
        with shard.lock:
            hist = shard.history
            if hist.maxlen is not None and len(hist) == hist.maxlen and hist:
                # about to evict the oldest event: resumes at or below its
                # rv can no longer be proven complete for this kind
                shard.floor = max(
                    shard.floor,
                    hist[0].obj.metadata.resource_version)
            hist.append(ev)
            with self._watch_lock:
                entries = self._kind_index.get(kind, ())
                ns = ev.obj.namespace
                interested = [
                    e for e in entries
                    if e.namespace is None or e.namespace == ns]
                interested += [
                    e for e in self._unfiltered
                    if e.namespace is None or e.namespace == ns]
                d = self._dispatch_counts
                delivered = len(interested)
                d[(kind, "delivered")] = \
                    d.get((kind, "delivered"), 0) + delivered
                d[(kind, "skipped")] = \
                    d.get((kind, "skipped"), 0) + \
                    (len(self._watch_entries) - delivered)
        errors = 0
        for e in interested:
            try:
                e.fn(ev)
            except Exception:
                # watcher isolation: the committing writer and the watcher
                # are different actors — in the sharded control plane
                # (kube/shard.py) a peer replica's map-event callback runs
                # on OUR commit path, and coupling our write to its bug
                # would turn one bad watcher into a fleet-wide outage.
                # Strict mode re-raises: tests and the model checker want
                # escaped-mutation traps and invariant failures loud.
                if self._strict:
                    raise
                errors += 1
                logging.getLogger("kubeflow_tpu.store").exception(
                    "watch callback failed for %s %s/%s",
                    kind, ev.obj.namespace, ev.obj.name)
        if errors:
            with shard.lock:
                with self._watch_lock:
                    d = self._dispatch_counts
                    d[(kind, "callback_errors")] = \
                        d.get((kind, "callback_errors"), 0) + errors

    def _next_rv(self) -> int:
        with self._rv_lock:
            self._rv_counter += 1
            return self._rv_counter

    # -- reads ----------------------------------------------------------------
    def get(self, kind: str, namespace: str, name: str) -> KubeObject:
        """Read one object.  Returns a PRIVATE copy — mutate it and
        update() it freely (the universal controller pattern)."""
        with self._fault_scope("get", kind, namespace, name) as faults:
            if faults and faults.get("stale"):
                stale = self._stale_of(kind, namespace, name)
                if stale is not None:
                    return stale
            shard = self._shard(kind)
            with shard.lock:
                obj = shard.objects.get((namespace, name))
                if obj is None:
                    raise NotFoundError(f"{kind} {namespace}/{name} not found")
                return obj.deepcopy()

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[KubeObject]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
        predicate: Optional[Callable[[str, str], bool]] = None,
    ) -> list[KubeObject]:
        """List objects of a kind.  Returns the stored objects themselves
        (copy-on-write contract): they are frozen shared snapshots —
        READ-ONLY.  To mutate one, get() a private copy and update() it;
        mutating a listed object in place is a bug (it would corrupt every
        other reader's view and defeat the store's no-op detection).
        `predicate(namespace, name)` filters server-side BEFORE results
        materialize — a sharded informer's resync lists only its owned
        keys instead of the whole fleet (the apiserver analog is a
        field/label selector evaluated in the watch cache)."""
        with self._fault_scope("list", kind, namespace or ""):
            shard = self._shard(kind)
            with shard.lock:
                return self._list_locked(shard, namespace, label_selector,
                                         predicate)

    @staticmethod
    def _list_locked(shard: _KindShard, namespace: Optional[str],
                     label_selector: Optional[dict[str, str]],
                     predicate: Optional[Callable[[str, str], bool]] = None
                     ) -> list[KubeObject]:
        out = []
        for (ns, name), obj in shard.objects.items():
            if namespace is not None and ns != namespace:
                continue
            if predicate is not None and not predicate(ns, name):
                continue
            if label_selector and not match_labels(
                    obj.metadata.labels, label_selector):
                continue
            out.append(obj)
        out.sort(key=lambda o: (o.namespace, o.name))
        return out

    def list_with_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
        predicate: Optional[Callable[[str, str], bool]] = None,
    ) -> tuple[list[KubeObject], int]:
        """List + the cluster resourceVersion as one atomic snapshot, so a
        list-then-watch client cannot miss events that land between the list
        and reading the rv (the apiserver returns both in one response).
        Same read-only and predicate contracts as list()."""
        with self._fault_scope("list", kind, namespace or ""):
            shard = self._shard(kind)
            with shard.lock:
                objs = self._list_locked(shard, namespace, label_selector,
                                         predicate)
                with self._rv_lock:
                    return objs, self._rv_counter

    # -- admission ------------------------------------------------------------
    def _admit(
        self, op: str, old: Optional[KubeObject], obj: KubeObject
    ) -> KubeObject:
        # hooks receive private copies (old may be the frozen stored
        # object; a hook must never be able to corrupt the store)
        old_copy: Optional[KubeObject] = None

        def old_for_hook() -> Optional[KubeObject]:
            nonlocal old_copy
            if old is not None and old_copy is None:
                old_copy = old.deepcopy()
            return old_copy

        for hook in self._mutating:
            if obj.kind in hook.kinds and op in hook.operations:
                mutated = hook.handler(op, old_for_hook(), obj.deepcopy())
                if mutated is not None:
                    obj = mutated
        for hook in self._validating:
            if obj.kind in hook.kinds and op in hook.operations:
                hook.handler(op, old_for_hook(), obj.deepcopy())  # raises AdmissionDenied
        return obj

    # -- writes ---------------------------------------------------------------
    def create(self, obj: KubeObject) -> KubeObject:
        with self._fault_scope("create", obj.kind, obj.metadata.namespace,
                               obj.metadata.name):
            return self._create(obj)

    def _create(self, obj: KubeObject) -> KubeObject:
        obj = obj.deepcopy()
        if not obj.metadata.name and obj.metadata.generate_name:
            with self._rv_lock:
                self._name_counter += 1
                seq = self._name_counter
            obj.metadata.name = f"{obj.metadata.generate_name}{seq:05x}"
        if not obj.metadata.name:
            raise InvalidError("metadata.name or generateName required")
        # admission OUTSIDE the store lock (as the apiserver runs webhook
        # callouts outside the etcd txn): a remote AdmissionReview handler may
        # re-enter this ApiServer from another thread.  Mutating hooks may
        # rewrite metadata, and the store must key the post-admission identity.
        obj = self._admit("CREATE", None, obj)
        shard = self._shard(obj.kind)
        key = (obj.metadata.namespace, obj.metadata.name)
        with shard.lock:
            if key in shard.objects:
                raise AlreadyExistsError(
                    f"{obj.kind} {key[0]}/{key[1]} already exists"
                )
            obj.metadata.uid = new_uid()
            obj.metadata.resource_version = self._next_rv()
            obj.metadata.generation = 1
            obj.metadata.creation_timestamp = now_iso()
            shard.objects[key] = obj  # canonical: frozen from here on
        self._notify(WatchEvent(EventType.ADDED, obj))
        # real k8s GC collects dependents whose owners are already gone (a
        # reconciler racing a cascade delete can create one — the GC's
        # attemptToDeleteItem handles exactly this); doing it synchronously
        # at create keeps the in-memory cluster deterministic
        self._collect_dangling_owners(obj)
        return obj.deepcopy()

    def _collect_dangling_owners(self, obj: KubeObject) -> None:
        """Strip ownerReferences whose owner no longer exists (by uid);
        delete the object outright when no live owner remains — the
        delete-racing-recreate fence that real GC provides.  Runs only at
        create, so a conflict must retry against fresh state here — a
        swallowed conflict would leave a dangling ref forever (and turn a
        later owner-deletion into a strip instead of a delete)."""
        if not obj.metadata.owner_references:
            return
        for _ in range(16):
            try:
                current = self.get(obj.kind, obj.namespace, obj.name)
            except NotFoundError:
                return  # someone else deleted it; done
            refs = current.metadata.owner_references
            live = []
            for r in refs:
                owner_shard = self._shard(r.kind)
                with owner_shard.lock:
                    owner = owner_shard.objects.get(
                        (current.namespace, r.name))
                if owner is not None \
                        and owner.metadata.uid == r.uid \
                        and owner.metadata.deletion_timestamp is None:
                    live.append(r)
            if len(live) == len(refs):
                return
            try:
                if live:
                    current.metadata.owner_references = live
                    self.update(current)
                else:
                    self.delete(current.kind, current.namespace, current.name)
                return
            except NotFoundError:
                return
            except ConflictError:
                continue  # concurrent writer; recompute from fresh state

    def update(self, obj: KubeObject, subresource: str = "") -> KubeObject:
        """Full-object update with optimistic concurrency.

        subresource="status" skips admission and generation bump, matching
        the /status subresource the reference writes via Status().Update()
        (notebook_controller.go:312).

        An EMPTY resourceVersion means "no precondition" (real-apiserver
        semantics): the write must replace unconditionally even under
        concurrency, so a commit-time conflict retries against fresh state
        — the analog of GuaranteedUpdate's internal retry."""
        with self._fault_scope("update", obj.kind, obj.metadata.namespace,
                               obj.metadata.name):
            return self._update(obj, subresource)

    def _update(self, obj: KubeObject, subresource: str = "") -> KubeObject:
        if not obj.metadata.resource_version:
            last: Exception | None = None
            for _ in range(16):
                try:
                    return self._update_once(obj.deepcopy(), subresource)
                except ConflictError as err:
                    last = err  # racer committed between read and CAS
            assert last is not None
            raise last
        return self._update_once(obj.deepcopy(), subresource)

    def _update_once(self, obj: KubeObject, subresource: str) -> KubeObject:
        key = (obj.metadata.namespace, obj.metadata.name)
        shard = self._shard(obj.kind)
        with shard.lock:
            old = shard.objects.get(key)
            if old is None:
                raise NotFoundError(f"{obj.kind} {key[0]}/{key[1]} not found")
        # `old` is the frozen canonical object — read-only from here on
        if not obj.metadata.resource_version:
            # real-apiserver semantics: an empty resourceVersion on update
            # means "no precondition" — the write replaces unconditionally
            # (clients that want optimistic concurrency send the RV they
            # read; all in-repo controllers do)
            obj.metadata.resource_version = old.metadata.resource_version
        if obj.metadata.resource_version != old.metadata.resource_version:
            raise ConflictError(
                f"{obj.kind} {key[0]}/{key[1]}: resourceVersion "
                f"{obj.metadata.resource_version} != {old.metadata.resource_version}"
            )
        if subresource == "status":
            merged = old.deepcopy()
            merged.body["status"] = copy_tree(obj.body.get("status", {}))
        else:
            merged = obj
            # status writes only through the status subresource
            # (copy_tree, not copy.deepcopy: the latter would preserve the
            # strict-mode FrozenDict wrappers of `old` into a private
            # object that must stay mutable)
            if "status" in old.body:
                merged.body["status"] = copy_tree(old.body["status"])
            elif "status" in merged.body:
                del merged.body["status"]
            # admission outside the lock (see create()); the commit below
            # re-checks the resourceVersion so a write that raced the
            # callout still conflicts, matching apiserver semantics
            merged = self._admit("UPDATE", old, merged)
            # name/namespace are immutable on update; keep keying sound
            merged.metadata.name = old.metadata.name
            merged.metadata.namespace = old.metadata.namespace
            if merged.body.get("spec") != old.body.get("spec"):
                merged.metadata.generation = old.metadata.generation + 1
            else:
                merged.metadata.generation = old.metadata.generation
        with shard.lock:
            current = shard.objects.get(key)
            if current is None:
                raise NotFoundError(f"{obj.kind} {key[0]}/{key[1]} not found")
            if current.metadata.resource_version != old.metadata.resource_version:
                raise ConflictError(
                    f"{obj.kind} {key[0]}/{key[1]}: object changed during "
                    "admission"
                )
            # immutable fields
            merged.metadata.uid = old.metadata.uid
            merged.metadata.creation_timestamp = old.metadata.creation_timestamp
            merged.metadata.deletion_timestamp = old.metadata.deletion_timestamp
            # no-op writes don't bump resourceVersion or wake watchers —
            # otherwise level-triggered loops (status sync) self-oscillate
            merged.metadata.resource_version = old.metadata.resource_version
            merged.frozen = False
            if merged.same_as(old):
                return old.deepcopy()
            merged.metadata.resource_version = self._next_rv()
            shard.objects[key] = merged  # canonical: frozen from here on
        self._notify(WatchEvent(EventType.MODIFIED, merged, prev=old))
        # finalizer removal on a deleting object may complete the delete
        if merged.metadata.deletion_timestamp and not merged.metadata.finalizers:
            self._finalize_delete(merged.kind, merged.namespace, merged.name)
            # the caller's view: the object as this update committed it
            return merged.deepcopy()
        return merged.deepcopy()

    def update_status(self, obj: KubeObject) -> KubeObject:
        return self.update(obj, subresource="status")

    def merge_patch(
        self, kind: str, namespace: str, name: str, patch: dict,
        view_out=None, view_in=None,
    ) -> KubeObject:
        """RFC 7386 merge patch; `None` values delete keys.  Used by the ODH
        controller's lock removal (merge-patch with null annotation value,
        odh notebook_controller.go:516-523).  Retries internally on conflict
        so callers never see one — the apiserver does the same for patch
        requests (it re-reads and re-applies server-side).

        view_out/view_in let the wire server apply the patch to a different
        API-version VIEW of the object (convert out, merge, convert back) —
        the apiserver's cross-version patch flow — without duplicating this
        retry loop: view_out(dict)->dict runs before the merge, view_in
        (KubeObject)->KubeObject after."""
        return self._patch_with_retry(
            kind, namespace, name, lambda base: _json_merge(base, patch),
            view_out, view_in)

    def strategic_merge_patch(
        self, kind: str, namespace: str, name: str, patch: dict,
        view_out=None, view_in=None,
    ) -> KubeObject:
        """Strategic merge patch: RFC 7386 shape plus patchMergeKey-keyed
        list merge and $patch/$deleteFromPrimitiveList directives
        (kube.strategicmerge).  Same server-side conflict retry and
        cross-version view hooks as merge_patch.  A malformed patch (list
        item missing its declared merge key) raises InvalidError — 422 on
        the wire, the apiserver's 'does not contain declared merge key'."""
        from .strategicmerge import strategic_merge

        def apply_smp(base: dict) -> dict:
            try:
                return strategic_merge(base, patch)
            except ValueError as err:
                raise InvalidError(str(err)) from None

        return self._patch_with_retry(
            kind, namespace, name, apply_smp, view_out, view_in)

    @staticmethod
    def _manifest_digest(applied: dict) -> str:
        """Content digest of an apply manifest (canonical JSON), keying the
        apply fast path."""
        return hashlib.sha256(
            json.dumps(applied, sort_keys=True,
                       separators=(",", ":"),
                       default=str).encode()).hexdigest()

    def apply(
        self, kind: str, namespace: str, name: str, applied: dict,
        field_manager: str, force: bool = False,
        view_out=None, view_in=None, return_created: bool = False,
    ) -> "KubeObject | tuple[KubeObject, bool]":
        """Server-side apply (kube/apply.py): upsert with managedFields
        ownership.  ApplyConflict surfaces as ConflictError (409 with the
        owning managers in the message); same conflict retry and
        cross-version view hooks as the other patch verbs.
        `return_created=True` returns (obj, created) so the wire layer can
        answer 201 for the create path without a racy pre-lookup.

        Fast path: when this field manager's previous apply of this object
        had the SAME manifest digest and the object still sits at the rv
        that apply produced, the whole merge machinery is skipped — the
        call is a proven no-op (a GitOps loop re-applying unchanged config
        on a timer costs one dict lookup per tick).  Any other writer
        bumping the object's rv invalidates the short-circuit."""
        with self._fault_scope("patch", kind, namespace, name):
            return self._apply(kind, namespace, name, applied, field_manager,
                               force, view_out, view_in, return_created)

    def _apply(
        self, kind: str, namespace: str, name: str, applied: dict,
        field_manager: str, force: bool = False,
        view_out=None, view_in=None, return_created: bool = False,
    ) -> "KubeObject | tuple[KubeObject, bool]":
        from .apply import (
            ApplyConflict,
            apply_update,
            field_set,
            sanitize_applied,
        )

        if not field_manager:
            raise InvalidError("fieldManager is required for apply")
        api_version = applied.get("apiVersion", "")
        applied = sanitize_applied(applied)
        # digest short-circuit (cross-version views excluded: the same
        # manifest can mean different stored state per view route)
        digest = ""
        obj_key = (kind, namespace, name)
        if view_out is None and view_in is None:
            digest = self._manifest_digest(applied)
            with self._apply_lock:
                prior = self._applied_digests.get(
                    obj_key, {}).get(field_manager)
            if prior is not None and prior[0] == digest:
                shard = self._shard(kind)
                with shard.lock:
                    cur = shard.objects.get((namespace, name))
                    if cur is not None and \
                            cur.metadata.resource_version == prior[1]:
                        out = cur.deepcopy()
                        return (out, False) if return_created else out
        last: Exception | None = None
        for _ in range(16):
            try:
                current = self.get(kind, namespace, name)
            except NotFoundError:
                # create path: the applied config becomes the object, with
                # this manager owning exactly what it applied
                obj = KubeObject.from_dict(copy.deepcopy(applied))
                obj.kind = kind
                obj.metadata.namespace = namespace
                obj.metadata.name = name
                obj.metadata.managed_fields = [{
                    "manager": field_manager,
                    "operation": "Apply",
                    "apiVersion": api_version or obj.api_version,
                    "fieldsType": "FieldsV1",
                    "fieldsV1": field_set(applied),
                    "time": now_iso(),
                }]
                if view_in is not None:
                    obj = view_in(obj)
                try:
                    created = self.create(obj)
                    self._record_apply(obj_key, field_manager, digest,
                                       created.metadata.resource_version)
                    return (created, True) if return_created else created
                except AlreadyExistsError as err:
                    last = err
                    continue  # raced another creator: re-apply onto it
            base = current.to_dict()
            if view_out is not None:
                base = view_out(base)
            try:
                merged_dict = apply_update(
                    base, applied, field_manager,
                    api_version or current.api_version,
                    force=force, now=now_iso())
            except ApplyConflict as err:
                raise ConflictError(str(err)) from None
            merged = KubeObject.from_dict(merged_dict)
            if view_in is not None:
                merged = view_in(merged)
            merged.metadata.resource_version = current.metadata.resource_version
            if merged.same_as(current):
                # semantic no-op apply (apply_update preserved the
                # managedFields timestamp for the unchanged field set):
                # skip the write path entirely — no admission callout, no
                # RV bump, no watch wakeup.
                self._record_apply(obj_key, field_manager, digest,
                                   current.metadata.resource_version)
                return (current, False) if return_created else current
            try:
                updated = self.update(merged)
                self._record_apply(obj_key, field_manager, digest,
                                   updated.metadata.resource_version)
                return (updated, False) if return_created else updated
            except ConflictError as err:
                last = err
            except NotFoundError as err:
                # a delete raced the read-modify-write: apply is an upsert,
                # so fall back to the create path on the next iteration
                last = err
        assert last is not None
        raise last

    def _record_apply(self, obj_key: tuple[str, str, str],
                      field_manager: str, digest: str, rv: int) -> None:
        if not digest:
            return
        with self._apply_lock:
            self._applied_digests.setdefault(
                obj_key, {})[field_manager] = (digest, rv)

    def json_patch(
        self, kind: str, namespace: str, name: str, ops: list,
        view_out=None, view_in=None,
    ) -> KubeObject:
        """RFC 6902 JSON Patch (application/json-patch+json) with the same
        server-side conflict retry and cross-version view hooks as
        merge_patch.  A failed `test` op raises InvalidError (the apiserver
        answers 422), and is NOT retried — the test expresses the caller's
        precondition, so retrying against fresh state would defeat it."""
        from .jsonpatch import PatchTestFailed, apply_patch

        def apply_ops(base: dict) -> dict:
            try:
                return apply_patch(base, ops)
            except PatchTestFailed as err:
                raise InvalidError(str(err)) from None
            except (KeyError, IndexError, TypeError, ValueError) as err:
                raise InvalidError(f"json patch failed: {err}") from None

        return self._patch_with_retry(
            kind, namespace, name, apply_ops, view_out, view_in)

    def _patch_with_retry(
        self, kind: str, namespace: str, name: str, apply_fn,
        view_out=None, view_in=None,
    ) -> KubeObject:
        """Shared patch protocol: read, apply `apply_fn` to the (possibly
        version-converted) dict view, write back pinned to the read RV, and
        retry the whole read-apply-write on conflict — the apiserver
        re-applies patches server-side the same way, so patch callers never
        see a ConflictError of their own making."""
        with self._fault_scope("patch", kind, namespace, name):
            return self._patch_with_retry_inner(
                kind, namespace, name, apply_fn, view_out, view_in)

    def _patch_with_retry_inner(
        self, kind: str, namespace: str, name: str, apply_fn,
        view_out=None, view_in=None,
    ) -> KubeObject:
        last: Exception | None = None
        for _ in range(16):
            current = self.get(kind, namespace, name)
            base = current.to_dict()
            if view_out is not None:
                base = view_out(base)
            patched = KubeObject.from_dict(apply_fn(base))
            if view_in is not None:
                patched = view_in(patched)
            patched.metadata.resource_version = current.metadata.resource_version
            try:
                return self.update(patched)
            except ConflictError as err:
                last = err
        assert last is not None
        raise last

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._fault_scope("delete", kind, namespace, name):
            self._delete(kind, namespace, name)

    def _delete(self, kind: str, namespace: str, name: str) -> None:
        shard = self._shard(kind)
        key = (namespace, name)
        with shard.lock:
            obj = shard.objects.get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    # replace, never mutate: `obj` is frozen shared state
                    updated = obj.deepcopy()
                    updated.metadata.deletion_timestamp = now_iso()
                    updated.metadata.resource_version = self._next_rv()
                    shard.objects[key] = updated
                    prev = obj
                else:
                    return  # already terminating
            else:
                updated = None
        if updated is not None:
            self._notify(WatchEvent(EventType.MODIFIED, updated, prev=prev))
            return
        self._finalize_delete(kind, namespace, name)

    def _finalize_delete(self, kind: str, namespace: str, name: str) -> None:
        shard = self._shard(kind)
        with shard.lock:
            obj = shard.objects.pop((namespace, name), None)
            if obj is None:
                return
            # deletion bumps the cluster resourceVersion (as in etcd) so the
            # DELETED watch event is ordered in the history window; the
            # popped canonical object stays untouched for anyone holding it
            deleted = obj.deepcopy()
            deleted.metadata.resource_version = self._next_rv()
        with self._apply_lock:
            self._applied_digests.pop((kind, namespace, name), None)
        self._notify(WatchEvent(EventType.DELETED, deleted))
        self._garbage_collect(deleted)

    def _garbage_collect(self, owner: KubeObject) -> None:
        """Background-cascade GC, matching real k8s semantics: drop the
        now-dangling ownerReference; delete the dependent only once its last
        owner is gone (same namespace only, as in real k8s GC)."""
        to_delete: list[tuple[str, str, str]] = []
        to_strip: list[KubeObject] = []
        with self._shards_lock:
            shards = list(self._shards.items())
        for kind, shard in shards:
            with shard.lock:
                for (ns, name), obj in shard.objects.items():
                    if ns != owner.namespace:
                        continue
                    refs = obj.metadata.owner_references
                    if not any(r.uid == owner.metadata.uid for r in refs):
                        continue
                    remaining = [r for r in refs if r.uid != owner.metadata.uid]
                    if remaining:
                        stripped = obj.deepcopy()
                        stripped.metadata.owner_references = remaining
                        to_strip.append(stripped)
                    else:
                        to_delete.append((kind, ns, name))
        for obj in to_strip:
            try:
                self.update(obj)
            except (NotFoundError, ConflictError):
                pass
        for kind, ns, name in to_delete:
            try:
                self.delete(kind, ns, name)
            except NotFoundError:
                pass

    # -- test/ops helpers ------------------------------------------------------
    def force_remove_finalizers(self, kind: str, namespace: str, name: str) -> None:
        obj = self.get(kind, namespace, name)
        obj.metadata.finalizers = []
        self.update(obj)

    def dump(self) -> dict[str, list[dict]]:
        with self._shards_lock:
            shards = list(self._shards.items())
        out: dict[str, list[dict]] = {}
        for kind, shard in shards:
            with shard.lock:
                out[kind] = [o.to_dict() for o in shard.objects.values()]
        return out


def _json_merge(base: dict, patch: dict) -> dict:
    out = copy.deepcopy(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _json_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out
