"""Compute-plane tests on the 8-device virtual CPU mesh (conftest.py):
mesh/sharding, attention numerics (incl. ring attention), sharded training,
and the model zoo — the in-notebook layer of the BASELINE matrix."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.configs import LLAMA2_7B, TINY
from kubeflow_tpu.models.mlp import train_mnist_steps
from kubeflow_tpu.models.train import mfu, setup_training
from kubeflow_tpu.models.transformer import Transformer, rope
from kubeflow_tpu.models.vit import VIT_TINY, ViT
from kubeflow_tpu.ops.attention import xla_attention
from kubeflow_tpu.ops.ring_attention import ring_attention
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_tpu.parallel.sharding import logical_to_spec


class TestMesh:
    def test_resolves_data_axis(self):
        mesh = make_mesh(MeshConfig(data=-1, fsdp=2, sequence=1, tensor=2))
        assert dict(mesh.shape) == {"data": 2, "fsdp": 2, "sequence": 1,
                                    "tensor": 2, "pipeline": 1, "expert": 1}

    def test_rejects_bad_factorization(self):
        with pytest.raises(ValueError):
            make_mesh(MeshConfig(data=3, fsdp=3, sequence=1, tensor=1))

    def test_multislice_mesh_trains(self):
        # BASELINE config #5 shape: DCN data parallel across 2 slices
        mesh = make_mesh(MeshConfig(data=4, fsdp=2, num_slices=2))
        setup = setup_training(TINY, mesh, batch_shape=(8, 64))
        batch = {
            "inputs": jnp.ones((8, 64), jnp.int32),
            "targets": jnp.ones((8, 64), jnp.int32),
        }
        _, metrics = setup.train_step(setup.state, batch)
        assert 0.0 < float(metrics["loss"]) < 20.0

    def test_logical_rules(self):
        # "embed" maps to fsdp, but batch already claimed it -> None
        spec = logical_to_spec(("batch", "seq", "embed"))
        assert spec == jax.sharding.PartitionSpec(
            ("data", "fsdp"), "sequence", None
        )
        # parameter tree case: no batch dim, embed keeps fsdp
        assert logical_to_spec(("embed", "mlp")) == jax.sharding.PartitionSpec(
            "fsdp", "tensor"
        )


class TestAttention:
    def _qkv(self, B=2, S=64, H=4, D=16, kv_heads=None):
        key = jax.random.PRNGKey(1)
        kq, kk, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, S, H, D))
        k = jax.random.normal(kk, (B, S, kv_heads or H, D))
        v = jax.random.normal(kv_, (B, S, kv_heads or H, D))
        return q, k, v

    def test_causal_masks_future(self):
        q, k, v = self._qkv()
        out1 = xla_attention(q, k, v, causal=True)
        # changing the future must not change position 0's output
        k2 = k.at[:, -1].set(99.0)
        v2 = v.at[:, -1].set(99.0)
        out2 = xla_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(out1[:, 0], out2[:, 0], rtol=1e-6)

    def test_ring_matches_reference(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))
        q, k, v = self._qkv(B=4, S=64)
        ref = xla_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_ring_gqa_and_grads(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))
        q, k, v = self._qkv(B=2, S=64, H=4, kv_heads=2)
        ref = xla_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g_ring = jax.grad(lambda a: jnp.sum(ring_attention(a, k, v, mesh) ** 2))(q)
        g_ref = jax.grad(lambda a: jnp.sum(xla_attention(a, k, v) ** 2))(q)
        np.testing.assert_allclose(g_ring, g_ref, atol=1e-4)

    def test_flash_block_sizes_clamped(self):
        # the Pallas tile config must clamp to the sequence so short
        # sequences and tuned tiles compose (ops/attention.py:_block_sizes);
        # numerics across these configs are gated on the real chip by
        # ci/flash_numerics.py
        from kubeflow_tpu.ops.attention import _block_sizes

        assert _block_sizes(0, 0, 2048, 2048) is None
        bs = _block_sizes(512, 1024, 256, 256)
        assert bs.block_q == 256 and bs.block_k == 256
        bs = _block_sizes(256, 512, 2048, 2048)
        assert (bs.block_q, bs.block_k, bs.block_k_major) == (256, 512, 512)
        assert (bs.block_q_dq, bs.block_k_dkv) == (256, 512)

    def test_rope_rotation_invariance(self):
        # same relative offset -> same attention scores
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 1, 16))
        pos_a = jnp.arange(4)[None, :]
        pos_b = pos_a + 7
        qa = rope(x, pos_a, 10_000.0)
        qb = rope(x, pos_b, 10_000.0)
        scores_a = jnp.einsum("bqhd,bkhd->bqk", qa, qa)
        scores_b = jnp.einsum("bqhd,bkhd->bqk", qb, qb)
        np.testing.assert_allclose(scores_a, scores_b, atol=1e-4)


class TestTraining:
    def test_sharded_train_step_runs_and_learns(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
        setup = setup_training(TINY, mesh, batch_shape=(8, 64))
        key = jax.random.PRNGKey(0)
        inputs = jax.random.randint(key, (8, 64), 0, TINY.vocab_size)
        batch = {"inputs": inputs, "targets": jnp.roll(inputs, -1, axis=1)}
        state = setup.state
        first = None
        for _ in range(5):
            state, metrics = setup.train_step(state, batch)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first  # memorizes the fixed batch

    def test_chunked_loss_matches_dense(self):
        mesh = make_mesh(MeshConfig(data=8))
        key = jax.random.PRNGKey(0)
        inputs = jax.random.randint(key, (8, 64), 0, TINY.vocab_size)
        batch = {"inputs": inputs, "targets": jnp.roll(inputs, -1, axis=1)}
        dense = setup_training(TINY, mesh, batch_shape=(8, 64))
        chunked = setup_training(
            TINY.with_(loss_chunks=4), mesh, batch_shape=(8, 64)
        )
        _, md = dense.train_step(dense.state, batch)
        _, mc = chunked.train_step(chunked.state, batch)
        assert abs(float(md["loss"]) - float(mc["loss"])) < 1e-4
        assert abs(float(md["grad_norm"]) - float(mc["grad_norm"])) < 1e-2

    def test_chunked_loss_tied_embeddings(self):
        mesh = make_mesh(MeshConfig(data=8))
        cfg = TINY.with_(tie_embeddings=True, logits_softcap=30.0)
        key = jax.random.PRNGKey(0)
        inputs = jax.random.randint(key, (8, 64), 0, cfg.vocab_size)
        batch = {"inputs": inputs, "targets": jnp.roll(inputs, -1, axis=1)}
        dense = setup_training(cfg, mesh, batch_shape=(8, 64))
        chunked = setup_training(
            cfg.with_(loss_chunks=4), mesh, batch_shape=(8, 64)
        )
        _, md = dense.train_step(dense.state, batch)
        _, mc = chunked.train_step(chunked.state, batch)
        assert abs(float(md["loss"]) - float(mc["loss"])) < 1e-4

    def test_ring_and_dense_training_agree(self):
        mesh_sp = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))
        mesh_dp = make_mesh(MeshConfig(data=8, fsdp=1, sequence=1, tensor=1))
        batch = {
            "inputs": jnp.ones((8, 64), jnp.int32),
            "targets": jnp.ones((8, 64), jnp.int32),
        }
        s1 = setup_training(TINY.with_(attention_impl="ring"), mesh_sp,
                            batch_shape=(8, 64))
        s2 = setup_training(TINY, mesh_dp, batch_shape=(8, 64))
        _, m1 = s1.train_step(s1.state, batch)
        _, m2 = s2.train_step(s2.state, batch)
        assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-3

    def test_remat_policies_identical_gradients(self):
        """Remat policies trade memory for recompute — they must NEVER
        change the math.  One step under each policy from identical init
        must produce identical loss and gradients (fp32 model, so exact
        comparison up to reduction noise)."""
        mesh = make_mesh(MeshConfig(data=8))
        key = jax.random.PRNGKey(3)
        inputs = jax.random.randint(key, (8, 64), 0, TINY.vocab_size)
        batch = {"inputs": inputs, "targets": jnp.roll(inputs, -1, axis=1)}
        results = {}
        for policy in ("nothing", "dots", "attn", "none"):
            setup = setup_training(TINY.with_(remat_policy=policy), mesh,
                                   batch_shape=(8, 64))
            _, m = setup.train_step(setup.state, batch)
            results[policy] = (float(m["loss"]), float(m["grad_norm"]))
        base = results["nothing"]
        for policy, (loss, gnorm) in results.items():
            assert abs(loss - base[0]) < 1e-5, (policy, loss, base[0])
            assert abs(gnorm - base[1]) < 1e-4, (policy, gnorm, base[1])

    def test_param_count_formula(self):
        mesh = make_mesh(MeshConfig(data=8))
        setup = setup_training(TINY, mesh, batch_shape=(2, 16))
        import flax.linen as nn

        actual = sum(
            x.size for x in jax.tree.leaves(nn.unbox(setup.state.params))
        )
        assert actual == TINY.num_params

    def test_llama7b_flops_accounting(self):
        # 7B config: ~6.74B params, known from the published architecture
        assert 6.5e9 < LLAMA2_7B.num_params < 7.0e9
        flops = LLAMA2_7B.flops_per_token(4096)
        assert 4.0e10 < flops < 5.5e10  # ~6N + attention
        # MFU: 1 token/s across 16 chips is tiny
        assert mfu(1.0, LLAMA2_7B, 4096, num_chips=16) < 1e-4

    def test_train_mfu_is_the_roofline_definition(self):
        # bench.py reports through models.train.mfu, the TelemetryAgent
        # through runtime.roofline — both must be the SAME number for the
        # same (config, tokens/s) or the headline forks
        from kubeflow_tpu.runtime.roofline import mfu as roofline_mfu

        for tok_s in (1.0, 2.8e4, 3.4e4):
            assert mfu(tok_s, LLAMA2_7B, 4096, num_chips=16) == \
                roofline_mfu(tok_s, LLAMA2_7B, 4096, 16)


class TestModelZoo:
    def test_mnist_mlp_learns(self):
        out = train_mnist_steps(num_steps=30)
        assert out["last_loss"] < out["first_loss"]

    def test_vit_forward(self):
        model = ViT(VIT_TINY)
        images = jnp.ones((2, 32, 32, 3))
        params = model.init(jax.random.PRNGKey(0), images)
        logits = model.apply(params, images)
        assert logits.shape == (2, 10)

    def test_transformer_unscanned_matches_scanned_shapes(self):
        cfg = TINY.with_(scan_layers=False)
        model = Transformer(cfg)
        tokens = jnp.ones((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)

    def test_gemma_style_softcap_and_tied_embeddings(self):
        cfg = TINY.with_(tie_embeddings=True, logits_softcap=30.0)
        model = Transformer(cfg)
        tokens = jnp.ones((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(params, tokens)
        assert "lm_head" not in params["params"]
        assert float(jnp.max(jnp.abs(logits))) <= 30.0


class TestGraftEntry:
    """The driver's multi-chip gate must stay green — and stay a
    CORRECTNESS gate (sharded updates allclose vs single-device), not just
    a compile check."""

    def test_dryrun_multichip_8(self):
        import __graft_entry__

        __graft_entry__.dryrun_multichip(8)

    def test_entry_compiles(self):
        import __graft_entry__

        fn, (params, tokens) = __graft_entry__.entry()
        logits = jax.jit(fn)(params, tokens)
        assert logits.shape[0] == tokens.shape[0]


class TestDecodeAttention:
    """Unit tests for the layout-native decode attention ops: both must
    equal the reference xla_attention over the logically-identical cache,
    across GQA groupings, fills, and the staged main/stage split."""

    def _ref(self, q, k_bshd, v_bshd, q_offset):
        from kubeflow_tpu.ops.attention import xla_attention

        return xla_attention(q, k_bshd, v_bshd, causal=True,
                             q_offset=q_offset)

    @pytest.mark.parametrize("kv_heads", [4, 2, 1])
    def test_matches_reference_layouts(self, kv_heads):
        from kubeflow_tpu.ops.attention import decode_attention

        B, S, H, D = 2, 24, 4, 8
        rng = jax.random.PRNGKey(0)
        q = jax.random.normal(rng, (B, 1, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, kv_heads, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, kv_heads, D))
        for offset in (0, 5, S - 1):
            got = decode_attention(
                q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
                q_offset=jnp.int32(offset))
            want = self._ref(q, k, v, jnp.int32(offset))
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("fill", [9, 16, 23])
    def test_staged_matches_merged(self, fill):
        from kubeflow_tpu.ops.attention import (
            decode_attention,
            decode_attention_staged,
        )

        B, S, KVH, D = 2, 24, 2, 8
        H = 4
        q = jax.random.normal(jax.random.PRNGKey(0), (B, 1, H, D))
        full_k = jax.random.normal(jax.random.PRNGKey(1), (B, KVH, S, D))
        full_v = jax.random.normal(jax.random.PRNGKey(2), (B, KVH, S, D))
        flushed = fill - fill % 8
        # main holds [0, flushed); stage slots [0, fill-flushed) hold the
        # tail; everything else garbage that masking must hide
        main_k = full_k.at[:, :, flushed:, :].set(99.0)
        main_v = full_v.at[:, :, flushed:, :].set(99.0)
        stage_k = jnp.full((B, KVH, 8, D), -77.0)
        stage_v = jnp.full((B, KVH, 8, D), -77.0)
        n_tail = fill - flushed
        if n_tail:
            stage_k = stage_k.at[:, :, :n_tail, :].set(
                full_k[:, :, flushed:fill, :])
            stage_v = stage_v.at[:, :, :n_tail, :].set(
                full_v[:, :, flushed:fill, :])
        got = decode_attention_staged(
            q, main_k, main_v, stage_k, stage_v,
            jnp.int32(flushed), jnp.int32(fill))
        want = decode_attention(q, full_k, full_v,
                                q_offset=jnp.int32(fill - 1))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
