"""Checkpoint-then-preempt: priority-based eviction under a write-ahead
record.

The admission gate (core/scheduler.py `_admission`) decides who may WAIT
for capacity; this module decides who must GIVE IT UP.  When a
higher-priority gang is stuck on the cold-provision path
(WARMPOOL_PROVISION_S away from chips), the scheduler asks the
PreemptionEngine whether evicting lower-priority tenants would free the
shortfall now.  The protocol is deliberately shaped like the other
state-destroying verbs in this codebase (selfheal's migrate, the
replicated tier's promote):

1. **Select** the cheapest set of victims: strictly lower priority rank
   than the beneficiary (never equal-or-higher), same accelerator/
   topology shape (evicting a different shape frees the wrong pool), not
   mid-cull (cull > preempt: a stop-annotated or Stopping/Stopped victim
   is already being parked — fighting the culler would double-handle the
   checkpoint), not already under a pending record, and — hard
   invariant — **checkpointed**: a final snapshot is requested while the
   slice can still flush, else the freshest stored snapshot within
   CHECKPOINT_MAX_AGE_S.  A victim whose state cannot be secured is
   skipped entirely; this codebase never tears down a session without
   its state in hand (the PR-6 guarantee, extended to eviction).

2. **Commit the write-ahead preemption record** into the cluster-scoped
   TenantQuota's status (`status.preemptions[victim]`, phase=Pending,
   carrying the full per-gang restore manifest) BEFORE anything is torn
   down — same optimistic-concurrency RMW pattern as TPUWarmPool.  A
   manager crash or shard failover anywhere after this point RESUMES the
   eviction (the "preemption" reconciler re-drives pending records off
   the TenantQuota watch + startup enqueue) and never repeats it: every
   step below is idempotent.

3. **Evict** each victim: persist its restore intent into
   `status.sessionState` (the migrate-verb machinery restores from it
   when the victim re-places) plus a queued annotation at the victim's
   OWN priority (reason="preempted", naming the beneficiary — the
   admission gate holds the victim out of the line until the beneficiary
   holds the placement it was evicted for), then tear the gang down
   slice-atomically: StatefulSets, every pod (errors aggregated — a
   partial teardown retries the WHOLE victim), pool claims released back
   to Ready, placement intent retired last.

4. **Finish** the record (phase=Done, folded into the bounded
   `status.recentPreemptions` audit trail) and let the pool watch wake
   the beneficiary: its cold Provisioning reservation upgrades onto the
   freed Ready slices (scheduler reservation-upgrade path).

Verb precedence across the codebase: cull > preempt > migrate > restart.
"""

from __future__ import annotations

import copy
import json
import logging
from typing import Optional

from ..api.types import PRIORITY_DEFAULT, Notebook
from ..kube import (
    AlreadyExistsError,
    ApiServer,
    EventRecorder,
    KubeObject,
    NotFoundError,
    ObjectMeta,
    Request,
    Result,
    retry_on_conflict,
)
from ..utils import tracing
from ..utils.clock import Clock
from ..utils.config import CoreConfig
from . import constants as C
from .metrics import NotebookMetrics
from .scheduler import (
    SliceScheduler,
    gang_chips,
    queued_info,
    rank_of,
    resolve_priority,
)
from .selfheal import SliceRestartError

logger = logging.getLogger("kubeflow_tpu.preemption")

_TRACER = tracing.get_tracer("kubeflow_tpu.core.preemption")

# preemption outcomes — bounded set, they label
# notebook_preemptions_total{result,priority}
PREEMPT_RESULT_EVICTED = "evicted"    # victim torn down by the live plan
PREEMPT_RESULT_RESUMED = "resumed"    # eviction re-driven after a crash
PREEMPT_RESULT_NO_VICTIM = "no-victim"  # eligible victims could not cover

# sessionState trigger for a preemption-driven restore — rides the same
# migrate-verb restore machinery and labels notebook_migrations_total
MIGRATE_TRIGGER_PREEMPT = "preempt"

# event reasons (kubectl describe notebook)
EVENT_PREEMPTED = "NotebookPreempted"
EVENT_PREEMPTION_ISSUED = "PreemptionIssued"

# bounded audit trail of completed evictions on TenantQuota status
RECENT_PREEMPTIONS_MAX = 16


def new_quota_object() -> KubeObject:
    """The cluster-scoped TenantQuota singleton, created empty on first
    use — operators fill spec.tenants/spec.defaults; the engine only
    needs the status side for its write-ahead records."""
    return KubeObject(
        api_version="kubeflow.org/v1",
        kind=C.TENANTQUOTA_KIND,
        metadata=ObjectMeta(name=C.TENANTQUOTA_NAME),
        body={"spec": {}},
    )


def pending_preemption(api: ApiServer, namespace: str, name: str) -> bool:
    """True while a write-ahead preemption record names this notebook as
    its victim.  The culling controller checks this before annotating a
    stop: a preemption in flight owns the victim's teardown and claim
    release — the culler must not race it."""
    quota = api.try_get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
    if quota is None:
        return False
    recs = (quota.body.get("status", {}) or {}).get("preemptions") or {}
    rec = recs.get(f"{namespace}/{name}")
    return bool(rec and rec.get("phase") == C.PREEMPTION_PENDING)


class PreemptionEngine:
    """Owns checkpoint-then-preempt end to end: victim selection,
    checkpoint securing, the write-ahead record, slice-atomic teardown,
    and crash resume.  Registered as the "preemption" reconciler
    for TenantQuota, so pending records re-drive on every manager start
    and on every record transition."""

    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        metrics: NotebookMetrics,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
        cache=None,
        session=None,
    ) -> None:
        self.api = api
        self.cfg = cfg
        self.metrics = metrics
        self.recorder = recorder or EventRecorder(api, "preemption")
        self.clock = clock or Clock()
        self.cache = cache
        if session is None and cfg.checkpoint_store_uri:
            from .sessionstate import open_store

            session = open_store(cfg.checkpoint_store_uri, clock=self.clock)
        self.session = session

    # -- entry point (called from the scheduler's wait path) ------------------
    def maybe_preempt(self, nb: Notebook, shape, chips_needed: float,
                      span) -> bool:
        """Plan and execute an eviction freeing `chips_needed` chips of
        `shape` capacity for `nb`, or do nothing.  Returns True when a
        covering plan committed.  Without a session store there is
        nothing to preempt with — eviction without a secured checkpoint
        is forbidden, full stop."""
        if not self.cfg.enable_preemption or self.session is None \
                or chips_needed <= 0:
            return False
        key = f"{nb.namespace}/{nb.name}"
        quota = self.api.try_get(
            C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        recs = {} if quota is None else (
            (quota.body.get("status", {}) or {}).get("preemptions") or {})
        if any(r.get("phase") == C.PREEMPTION_PENDING
               and r.get("beneficiary") == key for r in recs.values()):
            return False  # an earlier plan is in flight; resume owns it
        bpriority = resolve_priority(nb, quota)
        brank = rank_of(bpriority)
        reader = self.cache if self.cache is not None else self.api
        candidates: list[tuple] = []
        for obj in reader.list("Notebook"):
            vkey = f"{obj.namespace}/{obj.name}"
            if vkey == key or obj.metadata.deletion_timestamp is not None:
                continue
            ann = obj.metadata.annotations or {}
            if C.ANNOTATION_PLACEMENT not in ann:
                continue  # only placed gangs hold chips worth freeing
            # cull > preempt: a victim mid-cull is already being parked —
            # its pre-cull checkpoint handshake owns the teardown
            if C.STOP_ANNOTATION in ann:
                continue
            st = obj.body.get("status", {}) or {}
            if st.get("sliceHealth") in ("Stopping", "Stopped"):
                continue
            if (recs.get(vkey) or {}).get("phase") == C.PREEMPTION_PENDING:
                continue  # already someone's victim
            vtpu = obj.spec.get("tpu") or {}
            if str(vtpu.get("accelerator", "")) != shape.accelerator.name \
                    or str(vtpu.get("topology", "")) != shape.topology:
                continue  # evicting a different shape frees the wrong pool
            vp = resolve_priority(Notebook(obj), quota)
            if rank_of(vp) >= brank:
                continue  # never an equal-or-higher-priority victim
            chips = gang_chips(obj)
            if chips <= 0:
                continue
            candidates.append(
                (rank_of(vp), chips, obj.namespace, obj.name, vp, obj))
        if not candidates:
            return False  # nothing rank-eligible: the common, quiet case
        # cheapest set: lowest rank first, then fewest chips — evict the
        # least and the least-important; names break ties for determinism
        candidates.sort(key=lambda c: c[:4])
        plan: list[dict] = []
        freed = 0.0
        for _vrank, chips, vns, vname, vp, obj in candidates:
            if freed >= chips_needed:
                break
            gangs = self._secure_victim(obj, span)
            if gangs is None:
                continue  # no secured checkpoint -> never a victim
            plan.append({
                "key": f"{vns}/{vname}", "namespace": vns, "name": vname,
                "priority": vp, "chips": chips, "gangs": gangs,
                "beneficiary": key, "beneficiaryPriority": bpriority,
            })
            freed += chips
        if freed < chips_needed:
            # rank-eligible victims exist but cannot cover the shortfall
            # (or lack checkpoints): evict nobody — a partial eviction
            # would destroy sessions without unblocking the beneficiary
            self.metrics.preemptions.labels(
                PREEMPT_RESULT_NO_VICTIM, bpriority).inc()
            span.add_event("preempt.no_victim", {
                "needed": chips_needed, "securable": freed})
            return False
        self.preempt(nb, plan, span)
        return True

    # -- the write-ahead protocol ---------------------------------------------
    def preempt(self, nb: Notebook, plan: list[dict], span) -> None:
        """Execute a committed plan.  Protocol order IS the guarantee:
        the write-ahead record lands before ANY teardown (enforced by
        ci/analyzers/write_ahead.py), so a crash anywhere below resumes
        the eviction from the record — exactly once, never twice."""
        self._commit_record(nb, plan)
        for victim in plan:
            span.add_event("preempt.victim", {
                "victim": victim["key"], "priority": victim["priority"],
                "chips": victim["chips"]})
            self._persist_victim_intent(victim)
            self._teardown_victim(victim)
        self._finish_records(plan, PREEMPT_RESULT_EVICTED)
        for victim in plan:
            vobj = self.api.try_get(
                "Notebook", victim["namespace"], victim["name"])
            if vobj is not None:
                self.recorder.event(
                    vobj, "Warning", EVENT_PREEMPTED,
                    "preempted (%s) for higher-priority %s (%s); session "
                    "checkpointed, will restore on re-placement" % (
                        victim["priority"], victim["beneficiary"],
                        victim["beneficiaryPriority"]))
        self.recorder.event(
            nb.obj, "Normal", EVENT_PREEMPTION_ISSUED,
            "preempted %d lower-priority notebook(s) (%s) to free %.0f "
            "chip(s)" % (
                len(plan), ", ".join(v["key"] for v in plan),
                sum(v["chips"] for v in plan)))

    # -- crash resume ---------------------------------------------------------
    def reconcile(self, req: Request) -> Result:
        """Re-drive every pending preemption record.  Runs on manager
        start (enqueue_all) and on every TenantQuota transition, so an
        eviction interrupted between the record commit and the teardown
        completes under the next manager — idempotently: deletes
        tolerate NotFound, the restore intent re-persists byte-identical,
        claim release is a no-op once drained."""
        obj = self.api.try_get(C.TENANTQUOTA_KIND, "", req.name)
        if obj is None:
            return Result()
        recs = (obj.body.get("status", {}) or {}).get("preemptions") or {}
        pending = sorted(
            k for k, r in recs.items()
            if r.get("phase") == C.PREEMPTION_PENDING)
        if not pending:
            return Result()
        with _TRACER.start_span(
            "preempt.resume", {"phase": "preempt", "records": len(pending)},
        ) as span:
            plan: list[dict] = []
            for k in pending:
                rec = recs[k]
                ns, _, name = k.partition("/")
                plan.append({
                    "key": k, "namespace": ns, "name": name,
                    "priority": rec.get("victimPriority", PRIORITY_DEFAULT),
                    "chips": float(rec.get("chips", 0.0) or 0.0),
                    "gangs": copy.deepcopy(rec.get("restore") or {}),
                    "beneficiary": rec.get("beneficiary", ""),
                    "beneficiaryPriority": rec.get(
                        "beneficiaryPriority", PRIORITY_DEFAULT),
                })
            for victim in plan:
                span.add_event("preempt.resume", {"victim": victim["key"]})
                self._persist_victim_intent(victim)
                self._teardown_victim(victim)
            self._finish_records(plan, PREEMPT_RESULT_RESUMED)
        return Result()

    # -- steps ----------------------------------------------------------------
    def _secure_victim(self, obj: KubeObject, span) -> Optional[dict]:
        """Secure a restore manifest covering EVERY gang of the victim:
        a just-in-time final snapshot while the slice can still flush,
        else the freshest stored snapshot within CHECKPOINT_MAX_AGE_S.
        One uncoverable gang disqualifies the whole victim — there is no
        such thing as a partially-preserved session."""
        nb = Notebook(obj)
        tpu = nb.tpu
        if tpu is None:
            return None
        rep = nb.replication
        total = tpu.slices * (rep.replicas if rep else 1)
        now = self.clock.now()
        gangs: dict = {}
        for idx in range(total):
            snap = self.session.request_final_snapshot(
                nb.namespace, nb.name, idx)
            if snap is None:
                latest = self.session.latest(nb.namespace, nb.name, idx)
                if latest is None or \
                        now - latest.saved_at > self.cfg.checkpoint_max_age_s:
                    span.add_event("preempt.checkpoint_missing", {
                        "victim": f"{nb.namespace}/{nb.name}", "gang": idx})
                    return None
                snap = latest
            gangs[str(idx)] = {
                "restoreGeneration": snap.generation,
                "restoreUri": snap.uri,
                "digest": snap.digest,
                "savedAt": _iso_at(snap.saved_at),
            }
        return gangs

    def _commit_record(self, nb: Notebook, plan: list[dict]) -> None:
        """The write-ahead half: one Pending record per victim, carrying
        the full restore manifest, committed to TenantQuota status under
        conflict retry BEFORE any teardown.  Idempotent — a record that
        already rode in (resume) is left untouched."""
        bkey = f"{nb.namespace}/{nb.name}"

        def write() -> None:
            live = self._ensure_quota()
            st = copy.deepcopy(live.body.get("status") or {})
            recs = st.setdefault("preemptions", {})
            changed = False
            for victim in plan:
                cur = recs.get(victim["key"])
                if cur is not None and \
                        cur.get("phase") == C.PREEMPTION_PENDING:
                    continue
                recs[victim["key"]] = {
                    "victim": victim["key"],
                    "victimPriority": victim["priority"],
                    "beneficiary": bkey,
                    "beneficiaryPriority": victim["beneficiaryPriority"],
                    "chips": victim["chips"],
                    "phase": C.PREEMPTION_PENDING,
                    "createdAt": self.clock.now_iso(),
                    "restore": copy.deepcopy(victim["gangs"]),
                }
                changed = True
            if changed:
                live.status = st
                self.api.update_status(live)

        retry_on_conflict(write)

    def _persist_victim_intent(self, victim: dict) -> None:
        """Victim-side write-ahead, idempotent (re-run on resume): the
        restore intent into status.sessionState — the SAME record the
        migrate verb writes, so the existing restore machinery (STS
        restore stamping, restored-generation audit) carries the victim
        back — plus the queued annotation at the victim's own priority,
        naming the beneficiary so the admission fence holds."""
        ns, name = victim["namespace"], victim["name"]

        def write_status() -> None:
            try:
                live = self.api.get("Notebook", ns, name)
            except NotFoundError:
                return
            st = live.body.setdefault("status", {})
            before = copy.deepcopy(st.get("sessionState") or {})
            session = copy.deepcopy(before)
            for idx, rec in victim["gangs"].items():
                entry = dict(session.get(idx) or {})
                entry.update({
                    "restoreGeneration": rec["restoreGeneration"],
                    "restoreUri": rec["restoreUri"],
                    "digest": rec["digest"],
                    "savedAt": rec["savedAt"],
                    "trigger": MIGRATE_TRIGGER_PREEMPT,
                    "phase": "migrating",
                })
                entry.pop("restoredAt", None)
                session[idx] = entry
            if session != before:
                st["sessionState"] = session
                self.api.update_status(live)

        retry_on_conflict(write_status)

        def stamp_queued() -> None:
            try:
                live = self.api.get("Notebook", ns, name)
            except NotFoundError:
                return
            info = queued_info(live.metadata.annotations)
            changed = "since" not in info
            info.setdefault("since", self.clock.now())
            for field, value in (("priority", victim["priority"]),
                                 ("reason", "preempted"),
                                 ("beneficiary", victim["beneficiary"])):
                if info.get(field) != value:
                    info[field] = value
                    changed = True
            if changed:
                live.metadata.annotations[C.ANNOTATION_QUEUED] = json.dumps(
                    info, sort_keys=True, separators=(",", ":"))
                self.api.update(live)

        retry_on_conflict(stamp_queued)

    def _teardown_victim(self, victim: dict) -> None:
        """Slice-atomic teardown of one victim, strictly AFTER the record
        and the restore intent persisted.  StatefulSets go first (nothing
        recreates the pods), then every pod — errors aggregated so a
        transient failure retries the whole victim, never leaves it
        half-evicted and reported done — then the pool claims drain back
        to Ready (this is what wakes and feeds the beneficiary), and the
        placement intent retires last (claims before intent, same
        discipline as the scheduler's release path)."""
        ns, name = victim["namespace"], victim["name"]
        key = victim["key"]
        # duplicate-resume guard: if the record already folded to its
        # terminal phase, a racing manager finished this victim while we
        # were paused — running the teardown again could evict a gang
        # that legitimately re-placed after the fence lifted.  Leader
        # fencing keeps live managers from racing here in the first
        # place; this covers the zombie that wakes after losing it.
        if not pending_preemption(self.api, ns, name):
            return
        errors: list[Exception] = []
        attempted = 0
        for sts in list(self.api.list("StatefulSet", namespace=ns)):
            if not _owned_by(sts, name):
                continue
            try:
                self.api.delete("StatefulSet", ns, sts.name)
            except NotFoundError:
                pass
            except Exception as err:  # noqa: BLE001 — aggregated below
                errors.append(err)
        for pod in list(self.api.list(
                "Pod", namespace=ns,
                label_selector={C.NOTEBOOK_NAME_LABEL: name})):
            attempted += 1
            try:
                self.api.delete("Pod", ns, pod.name)
            except NotFoundError:
                pass
            except Exception as err:  # noqa: BLE001 — aggregated below
                errors.append(err)
        if errors:
            raise SliceRestartError(errors, attempted)

        for pool_obj in list(self.api.list(C.WARMPOOL_KIND)):
            held = (pool_obj.body.get("status", {}) or {}) \
                .get("slices") or {}
            if not any(e.get("claimedBy") == key for e in held.values()):
                continue

            def release(pool_name: str = pool_obj.name) -> None:
                live = self.api.get(C.WARMPOOL_KIND, "", pool_name)
                st = copy.deepcopy(live.body.get("status") or {})
                slices = st.setdefault("slices", {})
                changed = False
                for sid in list(slices):
                    if slices[sid].get("claimedBy") == key:
                        SliceScheduler._release_entry(slices, sid)
                        changed = True
                if changed:
                    live.status = st
                    self.api.update_status(live)

            retry_on_conflict(release)

        def drop_intent() -> None:
            try:
                live = self.api.get("Notebook", ns, name)
            except NotFoundError:
                return
            if C.ANNOTATION_PLACEMENT in live.metadata.annotations:
                del live.metadata.annotations[C.ANNOTATION_PLACEMENT]
                self.api.update(live)

        retry_on_conflict(drop_intent)

    def _finish_records(self, plan: list[dict], result: str) -> None:
        """Flip each victim's record to its terminal phase exactly once:
        out of status.preemptions, into the bounded recentPreemptions
        audit trail.  Metrics count only records THIS pass finished — a
        resume that finds a record already folded counts nothing."""
        finished: list[dict] = []

        def write() -> None:
            finished.clear()
            live = self.api.try_get(
                C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
            if live is None:
                return
            st = copy.deepcopy(live.body.get("status") or {})
            recs = st.get("preemptions") or {}
            recent = list(st.get("recentPreemptions") or [])
            changed = False
            for victim in plan:
                rec = recs.pop(victim["key"], None)
                if rec is None:
                    continue
                rec["phase"] = C.PREEMPTION_DONE
                rec["completedAt"] = self.clock.now_iso()
                recent.append(rec)
                finished.append(victim)
                changed = True
            if changed:
                if recs:
                    st["preemptions"] = recs
                else:
                    st.pop("preemptions", None)
                st["recentPreemptions"] = recent[-RECENT_PREEMPTIONS_MAX:]
                live.status = st
                self.api.update_status(live)

        retry_on_conflict(write)
        for victim in finished:
            self.metrics.preemptions.labels(
                result, victim["priority"]).inc()
            logger.info(
                "preemption %s: victim %s (%s) for %s", result,
                victim["key"], victim["priority"], victim["beneficiary"])

    # -- plumbing -------------------------------------------------------------
    def _ensure_quota(self) -> KubeObject:
        obj = self.api.try_get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        if obj is not None:
            return obj
        try:
            return self.api.create(new_quota_object())
        except AlreadyExistsError:
            return self.api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)


def _owned_by(sts: KubeObject, notebook: str) -> bool:
    ref = sts.metadata.controller_owner()
    if ref is not None and ref.kind == "Notebook":
        return ref.name == notebook
    return sts.metadata.labels.get(C.NOTEBOOK_NAME_LABEL) == notebook


def _iso_at(t: float) -> str:
    import time as _time

    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(t))


__all__ = [
    "EVENT_PREEMPTED",
    "EVENT_PREEMPTION_ISSUED",
    "MIGRATE_TRIGGER_PREEMPT",
    "PREEMPT_RESULT_EVICTED",
    "PREEMPT_RESULT_NO_VICTIM",
    "PREEMPT_RESULT_RESUMED",
    "PreemptionEngine",
    "new_quota_object",
    "pending_preemption",
]
