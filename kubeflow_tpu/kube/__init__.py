"""In-memory Kubernetes control-plane substrate (apiserver + controller
runtime + fake data plane) that the TPU notebook controllers run against."""

from .cache import InformerCache
from .cluster import FakeCluster, parse_quantity
from .controller import (
    BucketRateLimiter,
    ItemExponentialBackoff,
    Manager,
    MaxOfRateLimiter,
    Reconciler,
    Request,
    Result,
    WatchSpec,
    default_rate_limiter,
    is_status_only_update,
    suppress_status_only,
)
from .faults import FaultPlan, FaultRecord, FaultRule, random_fault_plan
from .errors import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    ForbiddenError,
    GoneError,
    InvalidError,
    NotFoundError,
    ServerError,
    is_already_exists,
    is_conflict,
    is_not_found,
    retry_on_conflict,
)
from .leader import LeaderElector
from .events import EventRecorder
from .meta import (
    KubeObject,
    ObjectMeta,
    OwnerReference,
    new_uid,
    set_controller_reference,
)
from .store import (
    AdmissionDenied,
    AdmissionHook,
    ApiServer,
    AuditRecord,
    EventType,
    WatchEvent,
)

__all__ = [
    "AdmissionDenied",
    "AdmissionHook",
    "AlreadyExistsError",
    "ApiError",
    "ApiServer",
    "AuditRecord",
    "BucketRateLimiter",
    "ConflictError",
    "EventRecorder",
    "EventType",
    "FakeCluster",
    "FaultPlan",
    "FaultRecord",
    "FaultRule",
    "ForbiddenError",
    "GoneError",
    "InformerCache",
    "InvalidError",
    "ItemExponentialBackoff",
    "KubeObject",
    "LeaderElector",
    "Manager",
    "MaxOfRateLimiter",
    "NotFoundError",
    "ServerError",
    "ObjectMeta",
    "OwnerReference",
    "Reconciler",
    "Request",
    "Result",
    "WatchEvent",
    "WatchSpec",
    "default_rate_limiter",
    "random_fault_plan",
    "is_already_exists",
    "is_conflict",
    "is_not_found",
    "is_status_only_update",
    "new_uid",
    "suppress_status_only",
    "parse_quantity",
    "retry_on_conflict",
    "set_controller_reference",
]
