"""COW/frozen contract: in-place mutation of shared store snapshots.

`api.list()` / `list_with_rv()` / `cache.list()` / `select()` /
`by_index()` return the frozen committed objects themselves (PR 8's
zero-copy read path).  Mutating one corrupts every other reader's view
and defeats no-op write suppression — the exact bug class PR 8 fixed by
hand in events.py, notebook_controller.py and cluster.py.

Intraprocedural taint dataflow, deliberately conservative:

  - a name bound from a freezing call is **container-tainted** (the
    returned list is a private container holding SHARED objects —
    sorting/appending the list itself is fine);
  - iterating or subscripting a container-tainted name yields
    **object-tainted** names; attribute/subscript paths off an
    object-tainted name (``labels = o.metadata.labels``) stay tainted;
  - flagged: assignment/del/augassign through a path rooted at an
    object-tainted name, mutator method calls (.append/.update/
    .setdefault/.pop/...) on such a path, and mutations reaching an
    element THROUGH a container (``objs[0].status[...] = x``);
  - any rebind through a call (``o = o.deepcopy()``, ``o = api.get(...)``)
    clears the taint — deepcopy/get are the sanctioned escape hatches.

Receivers considered freezing: a dotted chain ending in api/cache/
store/reader/client (``self.api.list``, ``cache.select``, ...).
"""

from __future__ import annotations

import ast

from . import Module, Violation, dotted

CHECK = "cow"

_FREEZING_METHODS = {"list", "list_with_rv", "select", "by_index"}
_API_RECEIVERS = {"api", "cache", "store", "reader", "client"}
_MUTATORS = {"append", "extend", "insert", "update", "setdefault", "pop",
             "popitem", "clear", "remove", "sort", "reverse", "add",
             "discard"}
_SEQ_WRAPPERS = {"sorted", "list", "reversed", "tuple"}


def _is_freezing_call(node) -> str:
    """'' or the method name when `node` is a frozen-snapshot read."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _FREEZING_METHODS):
        return ""
    recv = dotted(node.func.value)
    if recv and recv.split(".")[-1].lower() in _API_RECEIVERS:
        return node.func.attr
    return ""


def _root_name(node):
    """Root ast.Name of an Attribute/Subscript chain, with the step kinds
    walked ('attr'/'sub'), outermost last.  None root for dynamic."""
    steps = []
    while True:
        if isinstance(node, ast.Attribute):
            steps.append("attr")
            node = node.value
        elif isinstance(node, ast.Subscript):
            steps.append("sub")
            node = node.value
        else:
            break
    if isinstance(node, ast.Name):
        return node.id, list(reversed(steps))
    return None, []


class _FunctionChecker:
    def __init__(self, mod: Module, qualname: str):
        self.mod = mod
        self.qualname = qualname
        self.containers: set[str] = set()
        self.objects: set[str] = set()
        self.out: list[Violation] = []

    # -- taint computation ---------------------------------------------------
    def _value_taint(self, value) -> str:
        """'container' | 'object' | '' for an RHS expression."""
        if _is_freezing_call(value):
            return "container"
        if isinstance(value, ast.Name):
            if value.id in self.containers:
                return "container"
            if value.id in self.objects:
                return "object"
            return ""
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
                and value.func.id in _SEQ_WRAPPERS and value.args:
            if self._value_taint(value.args[0]) == "container":
                return "container"
            return ""
        if isinstance(value, ast.Subscript):
            inner = self._value_taint(value.value)
            if inner == "container":
                return "object"   # element extraction
            if inner == "object":
                return "object"   # subtree of a shared object
            return ""
        if isinstance(value, ast.Attribute):
            root, _ = _root_name(value)
            if root in self.objects:
                return "object"   # subtree handle (o.metadata.labels)
            return ""
        return ""

    def _bind(self, target, taint: str) -> None:
        if isinstance(target, ast.Name):
            self.containers.discard(target.id)
            self.objects.discard(target.id)
            if taint == "container":
                self.containers.add(target.id)
            elif taint == "object":
                self.objects.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # only list_with_rv-style unpack taints: (objs, rv) = ...
            for el in target.elts:
                self._bind(el, "")

    def _flag(self, node, what: str) -> None:
        self.out.append(Violation(
            CHECK, self.mod.rel, node.lineno, self.qualname,
            f"{what} mutates a frozen shared snapshot from "
            "list()/list_with_rv()/select()/by_index() — deepcopy() or "
            "get() a private copy first"))

    def _check_mutation_path(self, node, what: str) -> bool:
        """True when `node` (an Attribute/Subscript path) reaches shared
        state: rooted at an object-tainted name, or passing through an
        element of a container-tainted name."""
        root, steps = _root_name(node)
        if root is None:
            return False
        if root in self.objects:
            self._flag(node, what)
            return True
        # objs[0].status[...] — through-the-container element mutation:
        # the first step subscripts the container and the path continues
        if root in self.containers and len(steps) >= 2 and steps[0] == "sub":
            self._flag(node, what)
            return True
        return False

    # -- statement walk (source order, unions across branches) ---------------
    def run(self, body) -> None:
        self._visit_body(body)

    def _visit_body(self, stmts) -> None:
        for stmt in stmts:
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt) -> None:
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value)
            taint = self._value_taint(stmt.value)
            tuple_src = isinstance(stmt.value, ast.Call) and \
                isinstance(stmt.value.func, ast.Attribute) and \
                stmt.value.func.attr == "list_with_rv" and \
                _is_freezing_call(stmt.value)
            for target in stmt.targets:
                if isinstance(target, (ast.Attribute, ast.Subscript)):
                    self._check_mutation_path(target, "assignment")
                    self._scan_expr(target.value)
                elif tuple_src and isinstance(target, (ast.Tuple, ast.List)) \
                        and target.elts:
                    # objs, rv = api.list_with_rv(...): first element is
                    # the frozen container
                    self._bind(target.elts[0], "container")
                    for el in target.elts[1:]:
                        self._bind(el, "")
                else:
                    self._bind(target, taint)
        elif isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value)
            if isinstance(stmt.target, (ast.Attribute, ast.Subscript)):
                self._check_mutation_path(stmt.target, "augmented assignment")
            elif isinstance(stmt.target, ast.Name):
                self._bind(stmt.target, "")
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
                if isinstance(stmt.target, ast.Name):
                    self._bind(stmt.target, self._value_taint(stmt.value))
                elif isinstance(stmt.target, (ast.Attribute, ast.Subscript)):
                    self._check_mutation_path(stmt.target, "assignment")
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, (ast.Attribute, ast.Subscript)):
                    self._check_mutation_path(target, "del")
        elif isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter)
            taint = self._value_taint(stmt.iter)
            self._bind(stmt.target,
                       "object" if taint == "container" else "")
            # two passes: taint introduced late in the body applies to
            # earlier statements on the next iteration
            self._visit_body(stmt.body)
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._scan_expr(stmt.test)
            self._visit_body(stmt.body)
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self._scan_expr(stmt.test)
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            self._visit_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._visit_body(stmt.body)
            for h in stmt.handlers:
                self._visit_body(h.body)
            self._visit_body(stmt.orelse)
            self._visit_body(stmt.finalbody)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass  # nested scopes are analyzed separately
        # everything else: no taint effect

    def _scan_expr(self, expr) -> None:
        """Find mutator-method calls on tainted paths anywhere in an
        expression (comprehension bodies included, with their loop vars
        tainted when iterating a tainted source)."""
        for node in ast.walk(expr):
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                                 ast.DictComp)):
                for gen in node.generators:
                    if self._value_taint(gen.iter) == "container" and \
                            isinstance(gen.target, ast.Name):
                        self.objects.add(gen.target.id)
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS):
                continue
            recv = node.func.value
            if isinstance(recv, (ast.Attribute, ast.Subscript)):
                self._check_mutation_path(
                    recv, f".{node.func.attr}() call")
            elif isinstance(recv, ast.Name) and recv.id in self.objects:
                self._flag(node, f".{node.func.attr}() call")


def analyze(mod: Module) -> list[Violation]:
    out: list[Violation] = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                checker = _FunctionChecker(mod, qn)
                checker.run(child.body)
                # the loop-body double pass can report a site twice
                seen = {(v.line, v.message) for v in out}
                for v in checker.out:
                    if (v.line, v.message) not in seen:
                        seen.add((v.line, v.message))
                        out.append(v)
                walk(child, qn)
            elif isinstance(child, ast.ClassDef):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                walk(child, qn)
            else:
                walk(child, prefix)

    walk(mod.tree, "")
    return out
