"""Slice scheduler + warm pool (core/scheduler.py) and the FakeCluster
scheduling satellites: cost-function placement properties (gang atomicity,
co-location, spread), gang-gated rendering, warm-pool claim/release across
a manager failover, culling->reclamation, the hit-rate autoscaler, the
cordon->uncordon retry regression, and the incremental used-resources map
equivalence."""

from __future__ import annotations

import json
import random
import unittest

from kubeflow_tpu.api.types import Notebook, ReplicationSpec, TPUSpec
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.core.scheduler import (
    CostFunctionPolicy,
    NodeCapacity,
    SliceScheduler,
    parse_warmpool_shapes,
    placement_covers,
    placement_of,
    pool_object_name,
)
from kubeflow_tpu.core.workload import generate_statefulsets
from kubeflow_tpu.kube import (
    ApiServer,
    FakeCluster,
    KubeObject,
    Manager,
    ObjectMeta,
)
from kubeflow_tpu.tpu.topology import resolve
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig

V5E_4X4 = resolve("v5e", "4x4")      # 4 hosts x 4 chips
V5E_1X1 = resolve("v5e", "1x1")      # single host, 1 chip
SPEC = TPUSpec("v5e", "4x4")
POOL_NAME = pool_object_name("v5e", "4x4")


def scheduler_env(warm_size=0, shapes="", provision_s=120.0, extra=None):
    env = {
        "ENABLE_SLICE_SCHEDULER": "true",
        "WARMPOOL_SIZE": str(warm_size),
        "WARMPOOL_SHAPES": shapes,
        "WARMPOOL_PROVISION_S": f"{provision_s:g}",
    }
    env.update(extra or {})
    return CoreConfig.from_env(env)


def make_env(cfg=None, provisioner=True):
    api = ApiServer()
    cluster = FakeCluster(api)
    clock = FakeClock()
    mgr = Manager(api, clock=clock)
    cfg = cfg or scheduler_env()
    metrics = NotebookMetrics(api, manager=mgr)
    setup_core_controllers(mgr, cfg, metrics,
                           provisioner=cluster if provisioner else None)
    return api, cluster, clock, mgr, metrics


def pool_status(api):
    obj = api.try_get(C.WARMPOOL_KIND, "", POOL_NAME)
    return (obj.body.get("status") or {}) if obj is not None else {}


def stop_notebook(api, namespace, name):
    live = api.get("Notebook", namespace, name)
    live.metadata.annotations[C.STOP_ANNOTATION] = "true"
    api.update(live)


# -- placement policy ----------------------------------------------------------
class TestCostFunctionPolicy(unittest.TestCase):
    def _nodes(self, pool, n, free, total=4.0):
        return [NodeCapacity(f"{pool}-{i}", pool, free, total)
                for i in range(n)]

    def test_multi_host_packs_best_fit_pool(self):
        # pool-a fits exactly; pool-b leaves slack — best-fit picks a
        nodes = self._nodes("pool-a", 4, 4.0) + self._nodes("pool-b", 6, 4.0)
        gp = CostFunctionPolicy().place(V5E_4X4, nodes)
        self.assertIsNotNone(gp)
        self.assertEqual(gp.pool, "pool-a")
        self.assertEqual(len(gp.nodes), 4)

    def test_multi_host_never_partial(self):
        # neither pool alone fits the 4-host gang: placement must refuse
        # outright, not scatter workers across pools
        nodes = self._nodes("pool-a", 3, 4.0) + self._nodes("pool-b", 2, 4.0)
        self.assertIsNone(CostFunctionPolicy().place(V5E_4X4, nodes))

    def test_multi_host_skips_full_nodes(self):
        nodes = self._nodes("pool-a", 4, 4.0)
        nodes[0] = NodeCapacity("pool-a-0", "pool-a", 0.0, 4.0)
        self.assertIsNone(CostFunctionPolicy().place(V5E_4X4, nodes))

    def test_single_host_spreads(self):
        nodes = [NodeCapacity("n-0", "p", 1.0, 8.0),
                 NodeCapacity("n-1", "p", 7.0, 8.0),
                 NodeCapacity("n-2", "p", 3.0, 8.0)]
        gp = CostFunctionPolicy().place(V5E_1X1, nodes)
        self.assertEqual(gp.nodes, ("n-1",))  # most free chips wins

    def test_deterministic(self):
        rng = random.Random(7)
        nodes = [
            NodeCapacity(f"n-{i}", f"pool-{i % 5}",
                         float(rng.randint(0, 4)), 4.0)
            for i in range(40)
        ]
        policy = CostFunctionPolicy()
        first = policy.place(V5E_4X4, list(nodes))
        for _ in range(5):
            rng.shuffle(nodes)
            self.assertEqual(policy.place(V5E_4X4, list(nodes)), first)

    def test_property_gang_atomicity_and_colocation(self):
        """Randomized inventories: a returned placement is always a full
        co-located gang on fitting nodes; None only when genuinely no pool
        fits the whole gang."""
        policy = CostFunctionPolicy()
        for seed in range(200):
            rng = random.Random(seed)
            shape = resolve("v5e", rng.choice(["4x4", "4x8", "1x1", "2x2"]))
            nodes = [
                NodeCapacity(f"n-{i:02d}", f"pool-{rng.randint(0, 3)}",
                             float(rng.randint(0, 8)), 8.0)
                for i in range(rng.randint(0, 24))
            ]
            gp = policy.place(shape, nodes)
            by_name = {n.name: n for n in nodes}
            if gp is not None:
                self.assertEqual(len(gp.nodes), shape.num_hosts)
                self.assertEqual(len(set(gp.nodes)), shape.num_hosts)
                for name in gp.nodes:
                    self.assertEqual(by_name[name].pool, gp.pool)
                    self.assertGreaterEqual(by_name[name].free_chips,
                                            shape.chips_per_host)
            else:
                by_pool: dict[str, int] = {}
                for n in nodes:
                    if n.free_chips >= shape.chips_per_host:
                        by_pool[n.pool] = by_pool.get(n.pool, 0) + 1
                self.assertFalse(
                    any(k >= shape.num_hosts for k in by_pool.values()),
                    f"seed {seed}: a feasible pool was refused")


class TestParseShapes(unittest.TestCase):
    def test_parse(self):
        self.assertEqual(parse_warmpool_shapes("v5e:4x4, v5p:2x2x2"),
                         [("v5e", "4x4"), ("v5p", "2x2x2")])

    def test_malformed_skipped(self):
        self.assertEqual(
            parse_warmpool_shapes("v5e:4x4,nope,v9:1x1,v5e:4x4,:,x:"),
            [("v5e", "4x4")])


# -- gang gate + rendering -----------------------------------------------------
class TestGangGate(unittest.TestCase):
    def test_no_statefulset_until_placed(self):
        """The placement intent is written BEFORE any pod binds: while the
        cold provision is pending, zero StatefulSets exist and the status
        reads Scheduling — never a partially placed slice."""
        api, cluster, clock, mgr, _ = make_env(
            cfg=scheduler_env(provision_s=60.0))
        api.create(Notebook.new("nb", "default", tpu=SPEC).obj)
        mgr.run_until_idle()
        self.assertEqual(api.list("StatefulSet", namespace="default"), [])
        nb = api.get("Notebook", "default", "nb")
        self.assertEqual(nb.body["status"]["sliceHealth"], "Scheduling")
        self.assertNotIn(C.ANNOTATION_PLACEMENT, nb.metadata.annotations)
        # provision completes -> intent lands -> the whole gang binds
        mgr.advance(60.0)
        mgr.run_until_idle()
        nb = api.get("Notebook", "default", "nb")
        self.assertTrue(placement_covers(Notebook(nb), 1))
        self.assertEqual(nb.body["status"]["sliceHealth"], "Healthy")
        pods = [p for p in api.list("Pod", namespace="default")
                if p.spec.get("nodeName")]
        self.assertEqual(len(pods), V5E_4X4.num_hosts)
        pools = {
            api.get("Node", "", p.spec["nodeName"])
            .metadata.labels.get(C.GKE_NODEPOOL_LABEL)
            for p in pods
        }
        self.assertEqual(len(pools), 1)

    def test_placement_renders_nodeselector(self):
        nb = Notebook.new("nb", "default", tpu=SPEC)
        nb.metadata.annotations[C.ANNOTATION_PLACEMENT] = json.dumps(
            {"v": 1, "slices": {"0": {"pool": "pool-x"}}})
        sts = generate_statefulsets(nb, CoreConfig())[0]
        selector = sts.spec["template"]["spec"]["nodeSelector"]
        self.assertEqual(selector[C.GKE_NODEPOOL_LABEL], "pool-x")
        self.assertEqual(selector[C.GKE_TPU_ACCELERATOR_LABEL],
                         V5E_4X4.accelerator.gke_label)

    def test_placement_helpers_tolerate_garbage(self):
        self.assertEqual(placement_of({}), {})
        self.assertEqual(
            placement_of({C.ANNOTATION_PLACEMENT: "not-json"}), {})
        self.assertEqual(
            placement_of({C.ANNOTATION_PLACEMENT: "[1,2]"}), {})
        nb = Notebook.new("nb", "default", tpu=TPUSpec("v5e", "4x4", 2))
        nb.metadata.annotations[C.ANNOTATION_PLACEMENT] = json.dumps(
            {"v": 1, "slices": {"0": {"pool": "p"}}})
        self.assertFalse(placement_covers(nb, 2))  # slice 1 missing

    def test_multi_slice_bypass_never_double_books_nodes(self):
        """Regression: placing slice N of a gang must see the capacity
        claimed for slices 0..N-1 of the SAME notebook in the same pass
        as taken.  Two exact-fit pools + a 2-slice notebook used to land
        both slices on one pool (same node list twice — half the pods
        bound, notebook wedged Degraded while the other pool sat idle)."""
        api, cluster, clock, mgr, metrics = make_env()
        for prefix in ("ext-a", "ext-b"):
            cluster.add_tpu_slice_nodes(
                V5E_4X4.accelerator.gke_label, "4x4", 4, 4,
                name_prefix=prefix)
        api.create(Notebook.new(
            "nb", "default", tpu=TPUSpec("v5e", "4x4", 2)).obj)
        mgr.run_until_idle()  # bypass placement: no fake time needed
        nb = api.get("Notebook", "default", "nb")
        slices = placement_of(nb.metadata.annotations)
        self.assertEqual(len(slices), 2)
        self.assertNotEqual(slices["0"]["pool"], slices["1"]["pool"])
        self.assertFalse(
            set(slices["0"]["nodes"]) & set(slices["1"]["nodes"]))
        self.assertEqual(nb.body["status"]["sliceHealth"], "Healthy")
        bound = [p.spec["nodeName"]
                 for p in api.list("Pod", namespace="default")
                 if p.spec.get("nodeName")]
        self.assertEqual(len(bound), 2 * V5E_4X4.num_hosts)
        self.assertEqual(len(set(bound)), 2 * V5E_4X4.num_hosts)

    def test_bypass_places_on_preexisting_capacity(self):
        """Pre-existing (unmanaged) node pools are claimed through the
        cost-function bypass path: no warm pool, no provision delay."""
        api, cluster, clock, mgr, metrics = make_env()
        cluster.add_tpu_slice_nodes(
            V5E_4X4.accelerator.gke_label, "4x4", 4, 4, name_prefix="ext")
        api.create(Notebook.new("nb", "default", tpu=SPEC).obj)
        mgr.run_until_idle()  # no clock advance: placement must be instant
        nb = api.get("Notebook", "default", "nb")
        self.assertEqual(nb.body["status"]["sliceHealth"], "Healthy")
        st = pool_status(api)
        self.assertEqual(st["bypass"], 1)
        (entry,) = [e for e in st["slices"].values() if e.get("external")]
        self.assertEqual(entry["claimedBy"], "default/nb")
        self.assertEqual(metrics.warmpool_hits.value("bypass"), 1.0)


# -- warm pool: claim, failover, reclamation, autoscaler -----------------------
class TestReplicaAntiAffinity(unittest.TestCase):
    """Replicated notebooks (spec.replication): replica gangs must land on
    node pools disjoint from every other replica's, so one pool failure
    can never take the primary and its standby together."""

    def _rep_nb(self, anti_affine=True):
        return Notebook.new(
            "rep", "default", tpu=SPEC,
            replication=ReplicationSpec(replicas=2,
                                        anti_affine=anti_affine))

    def _gang_pools(self, api):
        nb = api.get("Notebook", "default", "rep")
        placement = placement_of(nb.metadata.annotations)
        return {gang: entry.get("pool") for gang, entry in placement.items()}

    def test_replica_gangs_placed_on_disjoint_pools(self):
        api, cluster, clock, mgr, _ = make_env()
        for pool in ("pool-a", "pool-b"):
            cluster.add_tpu_slice_nodes(
                "tpu-v5-lite-podslice", "4x4", V5E_4X4.num_hosts, 4,
                name_prefix=pool, pool=pool)
        api.create(self._rep_nb().obj)
        mgr.run_until_idle()
        pools = self._gang_pools(api)
        self.assertEqual(set(pools), {"0", "1"})
        self.assertEqual(set(pools.values()), {"pool-a", "pool-b"})
        # the bound pods agree with the intent, gang-atomically
        nb = api.get("Notebook", "default", "rep")
        self.assertEqual(nb.body["status"]["sliceHealth"], "Healthy")
        for sts, want in (("rep", pools["0"]), ("rep-r1", pools["1"])):
            gang = {f"{sts}-{i}" for i in range(V5E_4X4.num_hosts)}
            node_pools = {
                api.get("Node", "", p.spec["nodeName"])
                .metadata.labels.get(C.GKE_NODEPOOL_LABEL)
                for p in api.list("Pod", namespace="default")
                if p.name in gang and p.spec.get("nodeName")
            }
            self.assertEqual(node_pools, {want}, sts)

    def test_standby_refuses_to_share_the_primary_pool(self):
        """One pool with room for BOTH gangs: the standby must go cold
        (provision a fresh pool) rather than co-locate with the primary —
        capacity is not a reason to give up the failure domain."""
        api, cluster, clock, mgr, _ = make_env(
            cfg=scheduler_env(provision_s=60.0))
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    2 * V5E_4X4.num_hosts, 4)
        api.create(self._rep_nb().obj)
        mgr.run_until_idle()
        nb = api.get("Notebook", "default", "rep")
        self.assertEqual(nb.body["status"]["sliceHealth"], "Scheduling")
        self.assertFalse(placement_covers(Notebook(nb), 2))
        # the cold reservation lands after the provision delay
        mgr.advance(60.0)
        mgr.run_until_idle()
        pools = self._gang_pools(api)
        self.assertEqual(set(pools), {"0", "1"})
        self.assertNotEqual(pools["0"], pools["1"])
        nb = api.get("Notebook", "default", "rep")
        self.assertEqual(nb.body["status"]["sliceHealth"], "Healthy")

    def test_anti_affinity_off_allows_shared_pool(self):
        api, cluster, clock, mgr, _ = make_env()
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    2 * V5E_4X4.num_hosts, 4)
        api.create(self._rep_nb(anti_affine=False).obj)
        mgr.run_until_idle()
        pools = self._gang_pools(api)
        self.assertEqual(set(pools), {"0", "1"})
        self.assertEqual(pools["0"], pools["1"])


class TestWarmPool(unittest.TestCase):
    def _prewarmed(self, warm_size=2):
        cfg = scheduler_env(warm_size=warm_size, shapes="v5e:4x4")
        api, cluster, clock, mgr, metrics = make_env(cfg=cfg)
        mgr.settle(max_seconds=600.0)
        st = pool_status(api)
        self.assertEqual(
            [e["state"] for e in st["slices"].values()],
            ["Ready"] * warm_size)
        return api, cluster, clock, mgr, metrics, cfg

    def test_warm_claim_is_instant(self):
        api, cluster, clock, mgr, metrics, _ = self._prewarmed()
        t0 = clock.now()
        api.create(Notebook.new("nb", "default", tpu=SPEC).obj)
        mgr.run_until_idle()  # NO advance: a warm hit needs no fake time
        nb = api.get("Notebook", "default", "nb")
        self.assertEqual(nb.body["status"]["sliceHealth"], "Healthy")
        self.assertEqual(clock.now(), t0)
        self.assertEqual(pool_status(api)["hits"], 1)
        self.assertEqual(metrics.warmpool_hits.value("hit"), 1.0)

    def test_claim_release_idempotent_across_failover(self):
        """Pool bookkeeping lives on the API object: a fresh manager over
        the same store adopts the claims verbatim (no re-claim, no double
        accounting), and release still works post-failover."""
        api, cluster, clock, mgr, metrics, cfg = self._prewarmed()
        api.create(Notebook.new("nb", "default", tpu=SPEC).obj)
        mgr.settle(max_seconds=600.0)
        before = pool_status(api)
        annotation_before = api.get(
            "Notebook", "default", "nb").metadata.annotations[
                C.ANNOTATION_PLACEMENT]
        mgr.stop()
        # failover: new manager + controllers, same store and clock
        mgr2 = Manager(api, clock=clock)
        metrics2 = NotebookMetrics(api, manager=mgr2)
        setup_core_controllers(mgr2, cfg, metrics2, provisioner=cluster)
        mgr2.enqueue_all()
        mgr2.settle(max_seconds=600.0)
        after = pool_status(api)
        self.assertEqual(before["hits"], after["hits"])
        self.assertEqual(before["misses"], after["misses"])
        self.assertEqual(
            {sid: e.get("claimedBy") for sid, e in before["slices"].items()},
            {sid: e.get("claimedBy") for sid, e in after["slices"].items()})
        self.assertEqual(
            api.get("Notebook", "default", "nb")
            .metadata.annotations[C.ANNOTATION_PLACEMENT],
            annotation_before)
        # release through the NEW manager: claims made by the old one drain
        stop_notebook(api, "default", "nb")
        mgr2.settle(max_seconds=600.0)
        released = pool_status(api)
        self.assertFalse(any(e.get("claimedBy")
                             for e in released["slices"].values()))
        self.assertNotIn(
            C.ANNOTATION_PLACEMENT,
            api.get("Notebook", "default", "nb").metadata.annotations)

    def test_culling_reclamation_resells_the_slice(self):
        """A stopped notebook's slice drains back Ready with its nodes
        intact, and the next notebook claims the SAME slice as a hit."""
        api, cluster, clock, mgr, metrics, _ = self._prewarmed(warm_size=1)
        api.create(Notebook.new("first", "default", tpu=SPEC).obj)
        mgr.run_until_idle()
        claimed = {sid for sid, e in pool_status(api)["slices"].items()
                   if e.get("claimedBy") == "default/first"}
        self.assertEqual(len(claimed), 1)
        stop_notebook(api, "default", "first")
        mgr.settle(max_seconds=600.0)
        st = pool_status(api)
        sid = claimed.pop()
        self.assertEqual(st["slices"][sid]["state"], "Ready")
        nodes_before = st["slices"][sid]["nodes"]
        for n in nodes_before:  # capacity stayed provisioned (resold)
            self.assertIsNotNone(api.try_get("Node", "", n))
        api.create(Notebook.new("second", "default", tpu=SPEC).obj)
        mgr.run_until_idle()
        st = pool_status(api)
        self.assertEqual(st["slices"][sid]["claimedBy"], "default/second")
        self.assertEqual(
            api.get("Notebook", "default", "second")
            .body["status"]["sliceHealth"], "Healthy")

    def test_release_waits_for_checkpoint_on_cull(self):
        """Reclamation precedence: while the slice still reads Stopping
        (workers draining — a pre-cull checkpoint may be flushing), the
        claim and the intent stay put; only Stopped releases."""
        api = ApiServer()
        clock = FakeClock()
        cfg = scheduler_env()
        metrics = NotebookMetrics(api)
        sched = SliceScheduler(api, cfg, metrics, clock=clock)
        nb = Notebook.new("nb", "default", tpu=SPEC,
                          annotations={C.STOP_ANNOTATION: "true"})
        nb.metadata.annotations[C.ANNOTATION_PLACEMENT] = json.dumps(
            {"v": 1, "slices": {"0": {"pool": "warm-x"}}})
        api.create(nb.obj)
        api.create(KubeObject(
            api_version="kubeflow.org/v1", kind=C.WARMPOOL_KIND,
            metadata=ObjectMeta(name=POOL_NAME),
            body={"spec": {"accelerator": "v5e", "topology": "4x4"},
                  "status": {"slices": {"ws-0001": {
                      "state": "Claimed", "pool": "warm-x",
                      "claimedBy": "default/nb", "claimedSlice": 0}}}}))
        from kubeflow_tpu.kube import Request

        for health in ("Stopping", "Degraded"):
            live = api.get("Notebook", "default", "nb")
            live.status = {"sliceHealth": health}
            api.update_status(live)
            sched.reconcile(Request("default", "nb"))
            st = pool_status(api)
            self.assertEqual(st["slices"]["ws-0001"]["claimedBy"],
                             "default/nb", health)
            self.assertIn(
                C.ANNOTATION_PLACEMENT,
                api.get("Notebook", "default", "nb").metadata.annotations)
        live = api.get("Notebook", "default", "nb")
        live.status = {"sliceHealth": "Stopped"}
        api.update_status(live)
        sched.reconcile(Request("default", "nb"))
        self.assertIsNone(
            pool_status(api)["slices"]["ws-0001"].get("claimedBy"))
        self.assertNotIn(
            C.ANNOTATION_PLACEMENT,
            api.get("Notebook", "default", "nb").metadata.annotations)

    def test_orphan_claim_gc_on_notebook_delete(self):
        api, cluster, clock, mgr, metrics, _ = self._prewarmed(warm_size=1)
        api.create(Notebook.new("nb", "default", tpu=SPEC).obj)
        mgr.run_until_idle()
        self.assertTrue(any(e.get("claimedBy") == "default/nb"
                            for e in pool_status(api)["slices"].values()))
        api.delete("Notebook", "default", "nb")
        mgr.settle(max_seconds=600.0)
        self.assertFalse(any(e.get("claimedBy")
                             for e in pool_status(api)["slices"].values()))

    def test_autoscaler_grows_on_misses_and_decays_back(self):
        cfg = scheduler_env(warm_size=1, shapes="v5e:4x4",
                            extra={"WARMPOOL_DECAY_S": "60"})
        api, cluster, clock, mgr, metrics = make_env(cfg=cfg)
        mgr.settle(max_seconds=600.0)
        # 3 arrivals vs pool of 1: 1 hit + 2 misses -> target grows to 3
        for i in range(3):
            api.create(Notebook.new(f"nb-{i}", "default", tpu=SPEC).obj)
        mgr.run_until_idle()  # growth is immediate (event-driven)
        st = pool_status(api)
        self.assertEqual((st["hits"], st["misses"]), (1, 2))
        self.assertEqual(st["target"], 3)
        mgr.settle(max_seconds=1200.0)
        # stop everything: slices drain back idle; with zero misses across
        # the cooldown the target decays one step per WARMPOOL_DECAY_S all
        # the way back to the base, retiring the idle excess
        for i in range(3):
            stop_notebook(api, "default", f"nb-{i}")
        mgr.settle(max_seconds=1200.0)
        st = pool_status(api)
        self.assertEqual(st["target"], 1)
        idle = [e for e in st["slices"].values()
                if e.get("state") == "Ready" and not e.get("claimedBy")]
        self.assertEqual(len(idle), 1)

    def test_autoscaler_growth_bounded_by_max(self):
        cfg = scheduler_env(warm_size=1, shapes="v5e:4x4",
                            extra={"WARMPOOL_MAX_SIZE": "2"})
        api, cluster, clock, mgr, metrics = make_env(cfg=cfg)
        mgr.settle(max_seconds=600.0)
        for i in range(6):
            api.create(Notebook.new(f"nb-{i}", "default", tpu=SPEC).obj)
        mgr.settle(max_seconds=1200.0)
        self.assertLessEqual(pool_status(api)["target"], 2)

    def test_unmanaged_shape_retires_released_capacity(self):
        """Warm pool off for the shape: a released slice is torn back down
        (the cold path) instead of idling warm."""
        api, cluster, clock, mgr, metrics = make_env()  # no WARMPOOL_SHAPES
        api.create(Notebook.new("nb", "default", tpu=SPEC).obj)
        mgr.settle(max_seconds=600.0)
        nodes = [n.name for n in api.list("Node")]
        self.assertEqual(len(nodes), V5E_4X4.num_hosts)
        stop_notebook(api, "default", "nb")
        mgr.settle(max_seconds=600.0)
        self.assertEqual(pool_status(api).get("slices"), {})
        self.assertEqual([n.name for n in api.list("Node")], [])

    def test_warmpool_size_gauge_in_scrape(self):
        api, cluster, clock, mgr, metrics, _ = self._prewarmed(warm_size=2)
        body = metrics.scrape()
        self.assertIn(
            'notebook_warmpool_size{shape="v5e-4x4",state="Ready"} 2', body)
        self.assertIn(
            'notebook_warmpool_size{shape="v5e-4x4",state="Claimed"} 0',
            body)
        self.assertIn("notebook_schedule_attempts_total", body)

    def test_warmpool_size_gauge_zeroes_after_pool_delete(self):
        """A deleted TPUWarmPool's shape series must read 0 on the next
        scrape, not freeze at its last non-zero census."""
        api, cluster, clock, mgr, metrics, _ = self._prewarmed(warm_size=2)
        self.assertIn(
            'notebook_warmpool_size{shape="v5e-4x4",state="Ready"} 2',
            metrics.scrape())
        api.delete(C.WARMPOOL_KIND, "", POOL_NAME)
        self.assertIn(
            'notebook_warmpool_size{shape="v5e-4x4",state="Ready"} 0',
            metrics.scrape())


# -- FakeCluster satellites ----------------------------------------------------
class TestUncordonRetry(unittest.TestCase):
    def test_cordon_uncordon_reschedules_pending_pods(self):
        """Regression (satellite): pods left Pending by a cordon must be
        retried the moment the node is uncordoned — not whenever an
        unrelated node/capacity event happens by."""
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1", allocatable={"cpu": "8", "memory": "32Gi"})
        cluster.cordon_node("n1")
        sts = KubeObject(
            api_version="apps/v1", kind="StatefulSet",
            metadata=ObjectMeta(name="s", namespace="d"),
            body={"spec": {"replicas": 1, "template": {
                "spec": {"containers": [{"name": "c"}]}}}})
        api.create(sts)
        pod = api.get("Pod", "d", "s-0")
        self.assertEqual(pod.body["status"]["phase"], "Pending")
        self.assertFalse(pod.spec.get("nodeName"))
        cluster.uncordon_node("n1")
        pod = api.get("Pod", "d", "s-0")
        self.assertEqual(pod.spec.get("nodeName"), "n1")
        self.assertEqual(pod.body["status"]["phase"], "Running")

    def test_uncordon_of_unknown_or_uncordoned_node_is_noop(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.uncordon_node("ghost")  # must not raise
        cluster.add_node("n1")
        cluster.uncordon_node("n1")


class TestDeprovisionGuard(unittest.TestCase):
    def test_deprovision_skips_nodes_with_bound_pods(self):
        """deprovision_slice keys off the nodepool label alone; a node in
        the doomed pool that still hosts bound pods (a user-created pool
        sharing the label) must survive the teardown."""
        api = ApiServer()
        cluster = FakeCluster(api)
        for i in range(2):
            cluster.add_node(
                f"shared-{i}",
                labels={C.GKE_NODEPOOL_LABEL: "shared"},
                allocatable={"cpu": "8", "google.com/tpu": "4"})
        pod = KubeObject(
            api_version="v1", kind="Pod",
            metadata=ObjectMeta(name="p", namespace="d"),
            body={"spec": {
                "nodeName": "shared-0",
                "containers": [{"name": "c", "resources": {
                    "requests": {"google.com/tpu": "4"}}}]}})
        api.create(pod)
        cluster.deprovision_slice("shared")
        self.assertEqual([n.name for n in api.list("Node")], ["shared-0"])
        # once the pod is gone the node is reclaimable again
        api.delete("Pod", "d", "p")
        cluster.deprovision_slice("shared")
        self.assertEqual(api.list("Node"), [])


class TestIncrementalUsedMap(unittest.TestCase):
    """Satellite: FakeCluster._schedule reads an incrementally-maintained
    per-node used map instead of re-summing every pod per candidate node;
    the map must stay equivalent to the brute-force recount through any
    sequence of binds/deletes/rebinds."""

    @staticmethod
    def _brute_force(api, node_name):
        used: dict[str, float] = {}
        from kubeflow_tpu.kube import parse_quantity

        for p in api.list("Pod"):
            if p.spec.get("nodeName") != node_name:
                continue
            for c in p.spec.get("containers", []):
                for res, q in (c.get("resources", {})
                               .get("requests") or {}).items():
                    used[res] = used.get(res, 0.0) + parse_quantity(q)
        return used

    def _assert_equivalent(self, api, cluster, nodes):
        for name in nodes:
            self.assertEqual(cluster.node_used(name),
                             self._brute_force(api, name), name)

    def test_randomized_equivalence(self):
        rng = random.Random(20260804)
        api = ApiServer()
        cluster = FakeCluster(api, auto_ready=False)
        node_names = [f"n{i}" for i in range(4)]
        for name in node_names:
            cluster.add_node(name, allocatable={"cpu": "64",
                                                "memory": "256Gi",
                                                "google.com/tpu": "8"})
        live: list[str] = []
        counter = 0
        for step in range(300):
            op = rng.random()
            if op < 0.5 or not live:
                counter += 1
                name = f"p{counter}"
                res = rng.choice([{"cpu": "1"}, {"google.com/tpu": "4"},
                                  {"cpu": "2", "memory": "1Gi"}, {}])
                pod = KubeObject(
                    api_version="v1", kind="Pod",
                    metadata=ObjectMeta(name=name, namespace="d"),
                    body={"spec": {
                        "containers": [{"name": "c",
                                        "resources": {"requests": res}}]}})
                if rng.random() < 0.7:
                    pod.spec["nodeName"] = rng.choice(node_names)
                api.create(pod)
                live.append(name)
            elif op < 0.75:
                name = rng.choice(live)
                pod = api.get("Pod", "d", name)
                pod.spec["nodeName"] = rng.choice(node_names)
                api.update(pod)
            else:
                name = live.pop(rng.randrange(len(live)))
                api.delete("Pod", "d", name)
            if step % 10 == 0:
                self._assert_equivalent(api, cluster, node_names)
        self._assert_equivalent(api, cluster, node_names)

    def test_scheduler_respects_incremental_capacity(self):
        """End-to-end: binding through the kubelet path keeps capacity
        accounting exact — the third 4-chip pod that would overflow the
        8-chip node goes Pending."""
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("tpu-a", labels={
            C.GKE_TPU_ACCELERATOR_LABEL: "acc",
            C.GKE_TPU_TOPOLOGY_LABEL: "t"},
            allocatable={"cpu": "8", "memory": "8Gi", "google.com/tpu": "8"})
        for i in range(3):
            sts = KubeObject(
                api_version="apps/v1", kind="StatefulSet",
                metadata=ObjectMeta(name=f"s{i}", namespace="d"),
                body={"spec": {"replicas": 1, "template": {"spec": {
                    "nodeSelector": {C.GKE_TPU_ACCELERATOR_LABEL: "acc",
                                     C.GKE_TPU_TOPOLOGY_LABEL: "t"},
                    "containers": [{"name": "c", "resources": {
                        "requests": {"google.com/tpu": "4"}}}]}}}})
            api.create(sts)
        phases = sorted(
            p.body["status"]["phase"] for p in api.list("Pod", namespace="d"))
        self.assertEqual(phases, ["Pending", "Running", "Running"])
        self.assertEqual(cluster.node_used("tpu-a")["google.com/tpu"], 8.0)
        # freeing one slot lets exactly the pending pod in
        running = [p.name for p in api.list("Pod", namespace="d")
                   if p.body["status"]["phase"] == "Running"]
        api.delete("Pod", "d", running[0])
        api.delete("StatefulSet", "d", running[0][:-2])
        self.assertEqual(cluster.node_used("tpu-a")["google.com/tpu"], 8.0)


if __name__ == "__main__":
    unittest.main()
