"""Strategic merge patch semantics (kube/strategicmerge.py).

The reference's stack gets these semantics from the real apiserver
(kubectl sends application/strategic-merge-patch+json for core types);
here they are pinned directly: patchMergeKey-keyed list merge, $patch
directives, $deleteFromPrimitiveList, and the wire-server route.
"""

from __future__ import annotations

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.kube import ApiServer, KubeObject, ObjectMeta
from kubeflow_tpu.kube.client import KubeClient, RestConfig
from kubeflow_tpu.kube.strategicmerge import strategic_merge
from kubeflow_tpu.kube.wire import KubeApiWireServer


class TestKeyedListMerge:
    def test_containers_merge_by_name(self):
        base = {"containers": [
            {"name": "nb", "image": "a:1", "workingDir": "/home/jovyan"},
            {"name": "proxy", "image": "p:1"},
        ]}
        patch = {"containers": [{"name": "nb", "image": "a:2"}]}
        out = strategic_merge(base, patch)
        assert out["containers"] == [
            {"name": "nb", "image": "a:2", "workingDir": "/home/jovyan"},
            {"name": "proxy", "image": "p:1"},
        ], "keyed merge updates in place, keeps unmentioned siblings"

    def test_new_item_appended(self):
        base = {"containers": [{"name": "nb", "image": "a:1"}]}
        out = strategic_merge(
            base, {"containers": [{"name": "sidecar", "image": "s:1"}]})
        assert [c["name"] for c in out["containers"]] == ["nb", "sidecar"]

    def test_nested_env_merge(self):
        base = {"containers": [{"name": "nb", "env": [
            {"name": "A", "value": "1"}, {"name": "B", "value": "2"}]}]}
        patch = {"containers": [{"name": "nb", "env": [
            {"name": "B", "value": "20"}, {"name": "C", "value": "3"}]}]}
        out = strategic_merge(base, patch)
        assert out["containers"][0]["env"] == [
            {"name": "A", "value": "1"},
            {"name": "B", "value": "20"},
            {"name": "C", "value": "3"},
        ]

    def test_volume_mounts_key_on_mount_path(self):
        base = {"volumeMounts": [{"mountPath": "/data", "name": "v1"}]}
        patch = {"volumeMounts": [{"mountPath": "/data", "readOnly": True}]}
        out = strategic_merge(base, patch)
        assert out["volumeMounts"] == [
            {"mountPath": "/data", "name": "v1", "readOnly": True}]

    def test_ports_candidate_keys(self):
        # Container.ports keys on containerPort...
        base = {"ports": [{"containerPort": 8888}]}
        out = strategic_merge(
            base, {"ports": [{"containerPort": 8888, "name": "http"}]})
        assert out["ports"] == [{"containerPort": 8888, "name": "http"}]
        # ...ServiceSpec.ports on port
        base = {"ports": [{"port": 80, "targetPort": 8888}]}
        out = strategic_merge(
            base, {"ports": [{"port": 80, "name": "http-notebook"}]})
        assert out["ports"] == [
            {"port": 80, "targetPort": 8888, "name": "http-notebook"}]

    def test_unkeyed_list_replaced_atomically(self):
        base = {"args": ["--a"], "containers": [{"image": "no-name"}]}
        patch = {"args": ["--b"], "containers": [{"image": "x"}]}
        out = strategic_merge(base, patch)
        assert out["args"] == ["--b"]
        # when the BASE items lack the key too, atomic replace (no keyed
        # state to protect)
        assert out["containers"] == [{"image": "x"}]

    def test_missing_merge_key_rejected(self):
        # base items are keyed; a patch item omitting the declared merge
        # key must error like the apiserver, not silently replace the list
        base = {"containers": [{"name": "wb"}, {"name": "rbac-proxy"}]}
        with pytest.raises(ValueError, match="declared merge key"):
            strategic_merge(base, {"containers": [{"image": "x"}]})


class TestDirectives:
    def test_patch_delete_list_item(self):
        base = {"containers": [{"name": "nb"}, {"name": "proxy"}]}
        patch = {"containers": [{"name": "proxy", "$patch": "delete"}]}
        assert strategic_merge(base, patch)["containers"] == [{"name": "nb"}]

    def test_patch_replace_list(self):
        base = {"containers": [{"name": "a"}, {"name": "b"}]}
        patch = {"containers": [{"$patch": "replace"}, {"name": "c"}]}
        assert strategic_merge(base, patch)["containers"] == [{"name": "c"}]

    def test_patch_replace_map(self):
        base = {"resources": {"limits": {"cpu": "1"}, "requests": {"cpu": "1"}}}
        patch = {"resources": {"$patch": "replace", "limits": {"cpu": "2"}}}
        assert strategic_merge(base, patch)["resources"] == {
            "limits": {"cpu": "2"}}

    def test_delete_from_primitive_list(self):
        base = {"finalizers": ["a", "b", "c"]}
        patch = {"$deleteFromPrimitiveList/finalizers": ["b"]}
        assert strategic_merge(base, patch)["finalizers"] == ["a", "c"]

    def test_primitive_merge_union_with_deletions(self):
        # finalizers has patchStrategy=merge: additions union, deletions
        # apply last regardless of JSON key order (kubectl emits both in
        # one patch)
        base = {"finalizers": ["a", "b", "c"]}
        patch = {"finalizers": ["d"],
                 "$deleteFromPrimitiveList/finalizers": ["b"]}
        assert strategic_merge(base, patch)["finalizers"] == ["a", "c", "d"]
        reordered = {"$deleteFromPrimitiveList/finalizers": ["b"],
                     "finalizers": ["d"]}
        assert strategic_merge(base, reordered)["finalizers"] == [
            "a", "c", "d"], "deletion order-independent"

    def test_owner_references_merge_by_uid(self):
        base = {"metadata": {"ownerReferences": [
            {"uid": "A", "kind": "Notebook", "name": "wb"}]}}
        patch = {"metadata": {"ownerReferences": [
            {"uid": "B", "kind": "DSPA", "name": "dspa"}]}}
        out = strategic_merge(base, patch)
        assert [r["uid"] for r in out["metadata"]["ownerReferences"]] == [
            "A", "B"], "adding an owner must not sever existing owner links"

    def test_set_element_order_ignored(self):
        base = {"containers": [{"name": "a", "image": "i"}]}
        patch = {"$setElementOrder/containers": [{"name": "a"}],
                 "containers": [{"name": "a", "image": "j"}]}
        assert strategic_merge(base, patch)["containers"] == [
            {"name": "a", "image": "j"}]

    def test_null_deletes_key(self):
        out = strategic_merge({"a": 1, "b": 2}, {"a": None})
        assert out == {"b": 2}

    def test_directives_never_persist(self):
        # directives drive the merge but must not be stored (the apiserver
        # strips them): copy-fallback paths strip $patch keys and
        # pure-directive list items
        out = strategic_merge(
            {}, {"resources": {"$patch": "replace", "limits": {"cpu": "2"}}})
        assert out == {"resources": {"limits": {"cpu": "2"}}}
        out = strategic_merge(
            {"spec": {"containers": [{"name": "a"}]}},
            {"spec": {"containers": [{"name": "a", "image": "x"},
                                     {"$patch": "delete"}]}})
        assert out == {"spec": {"containers": []}}, \
            "key-less $patch: delete clears the keyed list"
        out = strategic_merge({}, {"x": {"$patch": "delete"}})
        assert out == {}, "map $patch: delete removes the key, not -> {}"
        out = strategic_merge(
            {"containers": [{"name": "a", "image": "i"}]},
            {"containers": [{"name": "a", "image": "j"},
                            {"$patch": "merge"}]})
        assert out["containers"] == [{"name": "a", "image": "j"}], \
            "unknown pure-directive items never become (empty) list items"

    def test_retain_keys(self):
        # kubectl emits $retainKeys for patchStrategy=retainKeys one-of
        # fields (e.g. Deployment .spec.strategy): after the merge only the
        # listed keys survive, and the directive itself is never stored
        base = {"strategy": {"type": "Recreate"}}
        patch = {"strategy": {
            "$retainKeys": ["type", "rollingUpdate"],
            "type": "RollingUpdate",
            "rollingUpdate": {"maxSurge": 1}}}
        assert strategic_merge(base, patch)["strategy"] == {
            "type": "RollingUpdate", "rollingUpdate": {"maxSurge": 1}}

    def test_inputs_not_mutated(self):
        base = {"containers": [{"name": "nb", "env": [{"name": "A"}]}]}
        patch = {"containers": [{"name": "nb",
                                 "env": [{"name": "B", "value": "2"}]}]}
        strategic_merge(base, patch)
        assert base == {"containers": [{"name": "nb", "env": [{"name": "A"}]}]}
        assert patch == {"containers": [{"name": "nb",
                                         "env": [{"name": "B", "value": "2"}]}]}


class TestInvariants:
    """Property-style invariants over generated pod-spec-shaped objects."""

    def _objects(self):
        # deterministic generator: nested maps, keyed + atomic lists
        for seed in range(8):
            n = seed % 3 + 1
            yield {
                "metadata": {"labels": {f"l{i}": str(i) for i in range(n)}},
                "spec": {
                    "replicas": seed,
                    "args": [f"--{i}" for i in range(n)],
                    "template": {"spec": {"containers": [
                        {"name": f"c{i}", "image": f"img:{seed}",
                         "env": [{"name": f"E{j}", "value": str(j)}
                                 for j in range(i + 1)]}
                        for i in range(n)]}},
                },
            }

    def test_empty_patch_is_identity(self):
        for obj in self._objects():
            assert strategic_merge(obj, {}) == obj

    def test_self_merge_is_identity(self):
        # merging an object into itself changes nothing: keyed lists merge
        # item-by-item, atomic lists replace with equal content
        for obj in self._objects():
            assert strategic_merge(obj, obj) == obj

    def test_merge_is_idempotent(self):
        patch = {"spec": {"template": {"spec": {"containers": [
            {"name": "c0", "image": "patched"}]}}}}
        for obj in self._objects():
            once = strategic_merge(obj, patch)
            assert strategic_merge(once, patch) == once


class TestOverTheWire:
    @pytest.fixture()
    def wire(self):
        api = ApiServer()
        srv = KubeApiWireServer(api).start()
        client = KubeClient(RestConfig(server=srv.url))
        yield api, client
        client.stop_informers()
        srv.stop()

    def test_strategic_patch_merges_containers(self, wire):
        _, client = wire
        nb = Notebook.new("wb", "default").obj
        nb.body["spec"]["template"]["spec"]["containers"] = [
            {"name": "wb", "image": "jupyter:1",
             "env": [{"name": "NB_PREFIX", "value": "/notebook/default/wb"}]},
        ]
        client.create(nb)
        client.strategic_merge_patch("Notebook", "default", "wb", {
            "spec": {"template": {"spec": {"containers": [
                {"name": "wb", "image": "jupyter:2"},
            ]}}}})
        got = client.get("Notebook", "default", "wb")
        (container,) = got.body["spec"]["template"]["spec"]["containers"]
        assert container["image"] == "jupyter:2"
        assert container["env"] == [
            {"name": "NB_PREFIX", "value": "/notebook/default/wb"}
        ], "keyed merge must not drop sibling fields (7386 would)"

    def test_strategic_patch_deletes_sidecar(self, wire):
        _, client = wire
        nb = Notebook.new("wb", "default").obj
        nb.body["spec"]["template"]["spec"]["containers"] = [
            {"name": "wb", "image": "jupyter:1"},
            {"name": "rbac-proxy", "image": "proxy:1"},
        ]
        client.create(nb)
        client.strategic_merge_patch("Notebook", "default", "wb", {
            "spec": {"template": {"spec": {"containers": [
                {"name": "rbac-proxy", "$patch": "delete"},
            ]}}}})
        got = client.get("Notebook", "default", "wb")
        names = [c["name"]
                 for c in got.body["spec"]["template"]["spec"]["containers"]]
        assert names == ["wb"]

    def test_store_direct_api(self):
        api = ApiServer()
        api.create(KubeObject(
            "v1", "ConfigMap", ObjectMeta(name="cm", namespace="ns"),
            body={"data": {"a": "1"}}))
        api.strategic_merge_patch("ConfigMap", "ns", "cm",
                                  {"data": {"b": "2"}})
        assert api.get("ConfigMap", "ns", "cm").body["data"] == {
            "a": "1", "b": "2"}
