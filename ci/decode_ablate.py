"""Decode overhead attribution: time structured ablations of the 470M
decode config and compare measured step-time deltas against the HBM
traffic each ablation removes.  A delta far above its traffic says the
removed component carries hidden cost (extra copies, serialization);
a delta at parity says it's already roofline-clean.

Usage: python ci/decode_ablate.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.configs import BENCH_CHIP
from kubeflow_tpu.models.generate import decode_config, generate
from kubeflow_tpu.models.transformer import Transformer

BATCH, PROMPT, NEW = 16, 128, 256


def streamed_bytes(cfg, batch):
    w = (cfg.num_params - cfg.vocab_size * cfg.embed_dim) * 2
    kv = (2 * batch * cfg.max_seq_len * cfg.num_kv_heads * cfg.head_dim
          * 2 * cfg.num_layers)
    return w, kv


def time_cfg(name, cfg, windows=3):
    model = Transformer(cfg)
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(rng, (BATCH, PROMPT), 0, cfg.vocab_size)
    params = jax.jit(model.init)(rng, prompt)["params"]
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    run = jax.jit(lambda p, t: generate(cfg, p, t, NEW))
    np.asarray(run(params, prompt))
    best = 0.0
    for i in range(windows):
        p = jax.random.randint(jax.random.PRNGKey(1000 + i),
                               (BATCH, PROMPT), 0, cfg.vocab_size)
        np.asarray(p)
        t0 = time.perf_counter()
        np.asarray(run(params, p))
        best = max(best, BATCH * NEW / (time.perf_counter() - t0))
    w, kv = streamed_bytes(cfg, BATCH)
    step_ms = BATCH / best * 1e3
    ideal_ms = (w + kv) / 819e9 * 1e3
    print(f"{name:34s} {best:8,.0f} tok/s  step={step_ms:6.3f}ms  "
          f"ideal={ideal_ms:6.3f}ms  gap={step_ms - ideal_ms:6.3f}ms  "
          f"(w={w / 1e6:.0f}MB kv={kv / 1e6:.0f}MB)")
    return step_ms


def main():
    base = decode_config(BENCH_CHIP).with_(max_seq_len=PROMPT + NEW)
    time_cfg("baseline 10L kv12 v32k", base)
    # halve KV traffic via GQA (weights shrink a little too — the ideal
    # column accounts for it)
    time_cfg("kv-heads 6 (KV/2)", base.with_(num_kv_heads=6))
    # halve the LM head + embedding
    time_cfg("vocab 16k (head/2)", base.with_(vocab_size=16_000))
    # half the layer stack: halves weights, KV, AND per-layer op count
    time_cfg("layers 5", base.with_(num_layers=5))
    # double batch: same weights, 2x KV, amortizes per-step fixed cost
    global BATCH
    BATCH = 32
    time_cfg("batch 32", base)


if __name__ == "__main__":
    main()
