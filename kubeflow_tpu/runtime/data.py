"""Input pipeline: host-sharded loading + device prefetch.

The data-plane side of the distributed runtime (SURVEY.md §2.5 — the
reference has no data path at all; its workload is a Jupyter server).  TPU
training is HBM- and host-bound long before it is loader-bound IF the
loader (a) only materializes each host's shard and (b) overlaps the
host->HBM transfer with the running step:

- `TokenBatches` yields deterministic host-local LM batches from a token
  array: seeded per-epoch shuffling, each process slicing its own rows of
  the global batch (`jax.process_index()` over the batch-sharded mesh
  axes), targets = inputs shifted.
- `ShardedBatcher` turns host-local numpy batches into GLOBAL jax Arrays
  via `jax.make_array_from_process_local_data` — the multi-host assembly
  that lets a pjit step consume per-host shards without any host ever
  holding the global batch.
- `DevicePrefetcher` stages N batches ahead onto device from a background
  thread (device_put is async; the queue depth hides transfer latency
  behind compute — the `prefetch_to_device` pattern generalized to
  NamedSharding).

Composed by `input_pipeline(...)`, the one-liner a notebook uses.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..parallel.sharding import logical_sharding


class TokenBatches:
    """Deterministic host-sharded LM batches from a flat token array.

    Each epoch draws `global_batch` non-overlapping sequence windows in a
    seeded shuffle; this process materializes ONLY rows
    [process_index * per_host, (process_index + 1) * per_host)."""

    def __init__(self, tokens: np.ndarray, global_batch: int, seq_len: int,
                 seed: int = 0, num_epochs: Optional[int] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None) -> None:
        self.tokens = np.asarray(tokens)
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.seed = seed
        self.num_epochs = num_epochs
        self.process_index = (process_index if process_index is not None
                              else jax.process_index())
        self.process_count = (process_count if process_count is not None
                              else jax.process_count())
        if global_batch % self.process_count != 0:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{self.process_count} processes")
        self.windows = (len(self.tokens) - 1) // seq_len
        if self.windows < global_batch:
            raise ValueError(
                f"dataset has {self.windows} windows of {seq_len}; "
                f"need >= {global_batch}")

    def __iter__(self) -> Iterator[dict]:
        per_host = self.global_batch // self.process_count
        lo = self.process_index * per_host
        epoch = 0
        while self.num_epochs is None or epoch < self.num_epochs:
            order = np.random.default_rng(
                (self.seed, epoch)).permutation(self.windows)
            for start in range(0, self.windows - self.global_batch + 1,
                               self.global_batch):
                mine = order[start + lo:start + lo + per_host]
                rows = np.stack([
                    self.tokens[w * self.seq_len:
                                w * self.seq_len + self.seq_len + 1]
                    for w in mine
                ])
                yield {"inputs": rows[:, :-1].astype(np.int32),
                       "targets": rows[:, 1:].astype(np.int32)}
            epoch += 1


class ShardedBatcher:
    """Host-local numpy batches -> global jax Arrays on the mesh."""

    def __init__(self, source, mesh: Mesh, rules=None,
                 logical_axes=("batch", None)) -> None:
        self.source = source
        self.mesh = mesh
        self.sharding: NamedSharding = logical_sharding(
            mesh, logical_axes, rules)

    def __iter__(self) -> Iterator[dict]:
        for batch in self.source:
            yield {
                k: jax.make_array_from_process_local_data(
                    self.sharding, np.asarray(v))
                for k, v in batch.items()
            }


class DevicePrefetcher:
    """Stage up to `depth` batches ahead from a background thread.

    device_put dispatches asynchronously; keeping a short queue of
    already-transferred batches means the step never waits on PCIe/DCN.
    Iteration ends when the source ends; `close()` tears the thread down
    early (e.g. on notebook interrupt)."""

    _DONE = object()

    def __init__(self, source, depth: int = 2,
                 transfer: Optional[Callable] = None) -> None:
        self.source = source
        self.transfer = transfer
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="data-prefetch")
        self._thread.start()

    def _loop(self) -> None:
        try:
            for batch in self.source:
                if self.transfer is not None:
                    batch = self.transfer(batch)
                while not self._stop.is_set():
                    try:
                        self._q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except Exception as err:  # surface loader errors to the consumer
            self._q.put(err)
            return
        self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stop.is_set():
                    raise StopIteration from None
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked producer can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


def input_pipeline(tokens: np.ndarray, global_batch: int, seq_len: int,
                   mesh: Mesh, seed: int = 0,
                   num_epochs: Optional[int] = None, prefetch: int = 2,
                   rules=None) -> DevicePrefetcher:
    """tokens -> prefetched, mesh-sharded {"inputs", "targets"} batches."""
    host = TokenBatches(tokens, global_batch, seq_len, seed=seed,
                        num_epochs=num_epochs)
    global_batches = ShardedBatcher(host, mesh, rules=rules)
    return DevicePrefetcher(global_batches, depth=prefetch)


__all__ = ["TokenBatches", "ShardedBatcher", "DevicePrefetcher",
           "input_pipeline"]
