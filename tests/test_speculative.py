"""Speculative decoding: EXACTNESS is the whole contract.

Greedy speculative output must be token-identical to the target's own
greedy decode — with a perfect draft (the target itself), with a
different tiny draft, and across batch rows (min-acceptance semantics).
The steps counter pins the speed mechanics: a perfect draft finishes in
~N/gamma rounds, a garbage draft degrades toward one token per round but
never changes the tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.generate import generate
from kubeflow_tpu.models.speculative import speculative_generate
from kubeflow_tpu.models.transformer import Transformer


def _params(cfg, seed=0):
    return Transformer(cfg).init(jax.random.PRNGKey(seed),
                                 jnp.ones((1, 8), jnp.int32))["params"]


class TestSpeculative:
    def _check_exact(self, draft_cfg, draft_params, gamma, n_new=12):
        cfg = TINY
        params = _params(cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                    cfg.vocab_size)
        ref = generate(cfg, params, prompt, max_new_tokens=n_new)
        out, steps = speculative_generate(
            cfg, params, draft_cfg, draft_params, prompt, n_new,
            gamma=gamma)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        return int(steps)

    def test_perfect_draft_is_exact_and_fast(self):
        """Draft == target: full acceptance every round -> ~N/(gamma-1+1)
        rounds (acceptance caps at gamma-1, +1 correction token)."""
        cfg = TINY
        params = _params(cfg)
        steps = self._check_exact(cfg, params, gamma=4, n_new=12)
        # 12 tokens, gamma-1=3 accepted + 1 correction per round = 4/round
        # (first token comes from prefill) -> ceil(11/4) = 3 rounds
        assert steps <= 4, steps

    def test_mismatched_draft_is_still_exact(self):
        """A differently-initialized draft (garbage agreement) must not
        change a single output token."""
        draft_cfg = TINY.with_(num_layers=1, embed_dim=32, num_heads=2,
                               num_kv_heads=1, head_dim=16, mlp_dim=64)
        draft_params = _params(draft_cfg, seed=7)
        steps = self._check_exact(draft_cfg, draft_params, gamma=4,
                                  n_new=12)
        # garbage draft: close to one token per round, never more than N
        assert steps <= 12, steps

    def test_gamma_guard(self):
        cfg = TINY
        params = _params(cfg)
        prompt = jnp.ones((1, 4), jnp.int32)
        try:
            speculative_generate(cfg, params, cfg, params, prompt, 4,
                                 gamma=1)
        except ValueError as e:
            assert "gamma" in str(e)
        else:
            raise AssertionError("gamma=1 should be rejected")


class TestSpeculativeSampling:
    """Rejection-sampling mode: the emitted distribution must equal
    target-only sampling — the draft may only change speed."""

    def _tiny(self):
        # vocab small enough to enumerate marginals exactly
        return TINY.with_(vocab_size=16)

    def test_smoke_and_accept_rate_range(self):
        from kubeflow_tpu.models.speculative import speculative_sample

        cfg = self._tiny()
        params = _params(cfg)
        draft_cfg = cfg.with_(num_layers=1, embed_dim=32, num_heads=2,
                              num_kv_heads=1, head_dim=16, mlp_dim=64)
        dparams = _params(draft_cfg, seed=7)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                    cfg.vocab_size)
        out, steps, rate = speculative_sample(
            cfg, params, draft_cfg, dparams, prompt, 10, gamma=4,
            temperature=0.8, rng=jax.random.PRNGKey(11))
        assert out.shape == (2, 16)
        assert int(steps) >= 1
        assert 0.0 <= float(rate) <= 1.0
        assert (np.asarray(out) >= 0).all()
        assert (np.asarray(out) < cfg.vocab_size).all()

    def test_perfect_draft_accepts_everything(self):
        from kubeflow_tpu.models.speculative import speculative_sample

        cfg = self._tiny()
        params = _params(cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0,
                                    cfg.vocab_size)
        _, steps, rate = speculative_sample(
            cfg, params, cfg, params, prompt, 12, gamma=4,
            temperature=1.0, rng=jax.random.PRNGKey(5))
        # p == q: acceptance prob min(1, p/q) = 1 -> every round advances
        # gamma-1 accepted + 1 emission; rate = (gamma-1)/gamma
        assert float(rate) >= 0.74, float(rate)
        assert int(steps) <= 4, int(steps)

    def test_distribution_matches_target_sampling(self):
        """Chi-square gate: the empirical marginal of the first TWO
        emitted tokens over many independent runs must match the
        target-enumerated marginal.  The draft is a DIFFERENT model, so
        rejections + residual resampling are genuinely exercised."""
        from kubeflow_tpu.models.speculative import speculative_sample

        cfg = self._tiny()
        params = _params(cfg)
        draft_cfg = cfg.with_(num_layers=1, embed_dim=32, num_heads=2,
                              num_kv_heads=1, head_dim=16, mlp_dim=64)
        dparams = _params(draft_cfg, seed=7)
        prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
        temperature = 1.0
        V = cfg.vocab_size

        # enumerate the target's exact marginals for positions P and P+1
        model = Transformer(cfg)
        logits = model.apply({"params": params}, prompt)
        p1 = jax.nn.softmax(
            logits[0, -1].astype(jnp.float32) / temperature)  # [V]
        exts = jnp.concatenate(
            [jnp.broadcast_to(prompt, (V, prompt.shape[1])),
             jnp.arange(V, dtype=jnp.int32)[:, None]], axis=1)
        logits2 = model.apply({"params": params}, exts)
        p2_cond = jax.nn.softmax(
            logits2[:, -1].astype(jnp.float32) / temperature, axis=-1)
        p2 = p1 @ p2_cond                                     # [V]

        n_trials = 1500
        run = jax.jit(lambda key: speculative_sample(
            cfg, params, draft_cfg, dparams, prompt, 2, gamma=2,
            temperature=temperature, rng=key)[0][0, -2:])
        keys = jax.random.split(jax.random.PRNGKey(42), n_trials)
        samples = np.asarray(jax.vmap(run)(keys))             # [N, 2]

        for pos, want in ((0, np.asarray(p1)), (1, np.asarray(p2))):
            counts = np.bincount(samples[:, pos], minlength=V)
            expected = want * n_trials
            # chi-square over bins with expected >= 5 (standard validity
            # rule); dof ~ bins-1, 99.9th percentile guard against flake
            mask = expected >= 5
            chi2 = float(np.sum(
                (counts[mask] - expected[mask]) ** 2 / expected[mask]))
            dof = int(mask.sum()) - 1
            from math import sqrt

            # chi2 99.9% quantile approx: dof + 3.1*sqrt(2*dof) + 9.5
            bound = dof + 3.1 * sqrt(2 * dof) + 9.5
            assert chi2 < bound, (pos, chi2, bound, dof)
