"""Deployment-manifest rendering: the kustomize plane as code.

The reference ships ~200 kustomize YAML files (components/*/config/: CRD
bases + conversion patches, RBAC, manager Deployment, webhook service/cert
plumbing, params.env ConfigMaps, overlays kubeflow/openshift/standalone).
Instead of a YAML tree we render the same objects from one Python module —
reviewable, testable, and parameterized by profile — and emit multi-doc YAML
via `python -m kubeflow_tpu.deploy`.
"""

from __future__ import annotations

from typing import Iterable

import yaml

from ..api.types import GROUP, VERSIONS
from ..tpu.topology import ACCELERATORS

PROFILES = ("kubeflow", "openshift", "standalone")


def _tpu_schema() -> dict:
    return {
        "type": "object",
        "required": ["accelerator", "topology"],
        "properties": {
            "accelerator": {
                "type": "string",
                "enum": sorted(ACCELERATORS),
                "description": "TPU generation",
            },
            "topology": {
                "type": "string",
                "pattern": r"^\d+x\d+(x\d+)?$",
                "description": "chip topology, e.g. 4x4 (v5e) or 2x2x2 (v5p)",
            },
            "slices": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": ">1 enables multi-slice DCN data parallelism",
            },
        },
    }


def notebook_crd(conversion_webhook: bool = True) -> dict:
    """The Notebook CRD: three field-identical versions, v1 storage, webhook
    conversion through the hub (reference config/crd/bases + patches)."""
    pod_spec = {
        "type": "object",
        "x-kubernetes-preserve-unknown-fields": True,
        "description": "raw corev1.PodSpec passthrough",
    }
    version_schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "template": {
                        "type": "object",
                        "properties": {"spec": pod_spec},
                    },
                    "tpu": _tpu_schema(),
                },
            },
            "status": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }
    versions = []
    for v in VERSIONS:
        versions.append(
            {
                "name": v,
                "served": True,
                "storage": v == "v1",
                "schema": {"openAPIV3Schema": version_schema},
                "subresources": {"status": {}},
            }
        )
    crd = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"notebooks.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": "Notebook",
                "listKind": "NotebookList",
                "plural": "notebooks",
                "singular": "notebook",
            },
            "scope": "Namespaced",
            "versions": versions,
        },
    }
    if conversion_webhook:
        crd["spec"]["conversion"] = {
            "strategy": "Webhook",
            "webhook": {
                "conversionReviewVersions": ["v1"],
                "clientConfig": {
                    "service": {
                        "name": "notebook-controller-webhook",
                        "namespace": "$(NAMESPACE)",
                        "path": "/convert",
                    }
                },
            },
        }
    return crd


def warmpool_crd() -> dict:
    """The TPUWarmPool CRD (core/scheduler.py): one cluster-scoped object
    per accelerator/topology shape; claim/release bookkeeping lives in its
    status subresource so it survives manager failover."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"tpuwarmpools.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": "TPUWarmPool",
                "listKind": "TPUWarmPoolList",
                "plural": "tpuwarmpools",
                "singular": "tpuwarmpool",
            },
            "scope": "Cluster",
            "versions": [
                {
                    "name": "v1",
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": {
                                    "type": "object",
                                    "properties": {
                                        "accelerator": {"type": "string"},
                                        "topology": {"type": "string"},
                                    },
                                },
                                "status": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields":
                                        True,
                                },
                            },
                        }
                    },
                    "subresources": {"status": {}},
                }
            ],
        },
    }


def rbac_role() -> dict:
    """ClusterRole covering both controllers (reference config/rbac/role.yaml
    union of core + odh markers)."""
    rules = [
        {"apiGroups": [GROUP], "resources": ["notebooks"],
         "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        {"apiGroups": [GROUP],
         "resources": ["notebooks/status", "notebooks/finalizers"],
         "verbs": ["get", "update", "patch"]},
        # slice scheduler + warm pool (core/scheduler.py): claim/release
        # bookkeeping lives on TPUWarmPool status
        {"apiGroups": [GROUP], "resources": ["tpuwarmpools"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": [GROUP], "resources": ["tpuwarmpools/status"],
         "verbs": ["get", "update", "patch"]},
        {"apiGroups": ["apps"], "resources": ["statefulsets"],
         "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        {"apiGroups": [""],
         "resources": ["services", "serviceaccounts", "secrets", "configmaps",
                        "events"],
         "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        {"apiGroups": [""], "resources": ["pods"],
         "verbs": ["get", "list", "watch", "delete"]},
        {"apiGroups": ["networking.k8s.io"], "resources": ["networkpolicies"],
         "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        {"apiGroups": ["gateway.networking.k8s.io"],
         "resources": ["httproutes", "referencegrants"],
         "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        {"apiGroups": ["gateway.networking.k8s.io"], "resources": ["gateways"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["rbac.authorization.k8s.io"],
         "resources": ["rolebindings", "clusterrolebindings"],
         "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        {"apiGroups": ["rbac.authorization.k8s.io"], "resources": ["roles",
                                                                    "clusterroles"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["image.openshift.io"], "resources": ["imagestreams"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["route.openshift.io"], "resources": ["routes"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["oauth.openshift.io"], "resources": ["oauthclients"],
         "verbs": ["get", "delete"]},
        {"apiGroups": ["config.openshift.io"], "resources": ["proxies",
                                                              "apiservers"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["datasciencepipelinesapplications.opendatahub.io"],
         "resources": ["datasciencepipelinesapplications"],
         "verbs": ["get", "list", "watch"]},
        # leader election (main.py --enable-leader-election; reference
        # leader-election RBAC in config/rbac/leader_election_role.yaml)
        {"apiGroups": ["coordination.k8s.io"], "resources": ["leases"],
         "verbs": ["get", "list", "watch", "create", "update", "patch"]},
    ]
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "notebook-controller-role"},
        "rules": rules,
    }


def manager_deployment(profile: str, image: str = "kubeflow-tpu-controller:latest") -> dict:
    """Manager Deployment (reference config/manager/manager.yaml), env fed by
    the params ConfigMap."""
    env = [
        {"name": "ENABLE_CULLING", "valueFrom": {"configMapKeyRef": {
            "name": "notebook-controller-params", "key": "ENABLE_CULLING",
            "optional": True}}},
        {"name": "CULL_IDLE_TIME", "valueFrom": {"configMapKeyRef": {
            "name": "notebook-controller-params", "key": "CULL_IDLE_TIME",
            "optional": True}}},
        {"name": "IDLENESS_CHECK_PERIOD", "valueFrom": {"configMapKeyRef": {
            "name": "notebook-controller-params", "key": "IDLENESS_CHECK_PERIOD",
            "optional": True}}},
        {"name": "CHECKPOINT_BEFORE_CULL", "valueFrom": {"configMapKeyRef": {
            "name": "notebook-controller-params", "key": "CHECKPOINT_BEFORE_CULL",
            "optional": True}}},
        {"name": "TPU_DEFAULT_IMAGE", "valueFrom": {"configMapKeyRef": {
            "name": "notebook-controller-params", "key": "TPU_DEFAULT_IMAGE",
            "optional": True}}},
        {"name": "K8S_NAMESPACE", "valueFrom": {
            "fieldRef": {"fieldPath": "metadata.namespace"}}},
    ]
    if profile == "openshift":
        env.append({"name": "SET_PIPELINE_RBAC", "value": "true"})
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": "notebook-controller-deployment",
            "labels": {"app": "notebook-controller"},
        },
        "spec": {
            # two replicas double-reconcile without election; the manager
            # runs --enable-leader-election so the standby is a hot spare
            # (notebook-controller/main.go:91-93)
            "replicas": 2,
            "selector": {"matchLabels": {"app": "notebook-controller"}},
            "template": {
                "metadata": {"labels": {"app": "notebook-controller"}},
                "spec": {
                    "serviceAccountName": "notebook-controller-sa",
                    "containers": [
                        {
                            "name": "manager",
                            "image": image,
                            "command": [
                                "python", "-m", "kubeflow_tpu.main",
                                "--in-cluster",
                                "--enable-leader-election",
                                "--cert-dir",
                                "/tmp/k8s-webhook-server/serving-certs",
                            ],
                            "ports": [
                                {"name": "metrics", "containerPort": 8080},
                                {"name": "webhook", "containerPort": 9443},
                            ],
                            "livenessProbe": {
                                "httpGet": {"path": "/healthz", "port": 8080}
                            },
                            "readinessProbe": {
                                "httpGet": {"path": "/readyz", "port": 8080}
                            },
                            "volumeMounts": [{
                                "name": "serving-certs",
                                "mountPath":
                                    "/tmp/k8s-webhook-server/serving-certs",
                                "readOnly": True,
                            }],
                            "resources": {
                                "requests": {"cpu": "100m", "memory": "128Mi"},
                                "limits": {"cpu": "500m", "memory": "512Mi"},
                            },
                        }
                    ],
                    "volumes": [{
                        "name": "serving-certs",
                        "secret": {
                            "secretName": "notebook-controller-webhook-certs",
                            "optional": True,
                        },
                    }],
                },
            },
        },
    }


def params_configmap(profile: str) -> dict:
    data = {
        "ENABLE_CULLING": "false",
        "CULL_IDLE_TIME": "1440",
        "IDLENESS_CHECK_PERIOD": "1",
        "CHECKPOINT_BEFORE_CULL": "true",
        "TPU_DEFAULT_IMAGE": "jupyter-tpu-jax:latest",
    }
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": "notebook-controller-params"},
        "data": data,
    }


def webhook_manifests() -> list[dict]:
    """Mutating + validating webhook configs and the serving Service
    (reference config/webhook/)."""
    client_config = {
        "service": {
            "name": "notebook-controller-webhook",
            "namespace": "$(NAMESPACE)",
        }
    }
    return [
        {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "notebook-controller-mutating"},
            "webhooks": [
                {
                    "name": "mutate-notebook-v1.kubeflow.org",
                    "admissionReviewVersions": ["v1"],
                    "sideEffects": "NoneOnDryRun",
                    "clientConfig": {
                        **client_config,
                        "service": {
                            **client_config["service"],
                            "path": "/mutate-notebook-v1",
                        },
                    },
                    "rules": [
                        {
                            "apiGroups": [GROUP],
                            "apiVersions": ["v1"],
                            "operations": ["CREATE", "UPDATE"],
                            "resources": ["notebooks"],
                        }
                    ],
                }
            ],
        },
        {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "notebook-controller-validating"},
            "webhooks": [
                {
                    "name": "validate-notebook-v1.kubeflow.org",
                    "admissionReviewVersions": ["v1"],
                    "sideEffects": "None",
                    "clientConfig": {
                        **client_config,
                        "service": {
                            **client_config["service"],
                            "path": "/validate-notebook-v1",
                        },
                    },
                    "rules": [
                        {
                            "apiGroups": [GROUP],
                            "apiVersions": ["v1"],
                            "operations": ["UPDATE"],
                            "resources": ["notebooks"],
                        }
                    ],
                }
            ],
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": "notebook-controller-webhook"},
            "spec": {
                "selector": {"app": "notebook-controller"},
                "ports": [{"port": 443, "targetPort": 9443}],
            },
        },
    ]


def render_profile(profile: str = "standalone",
                   image: str = "kubeflow-tpu-controller:latest") -> list[dict]:
    if profile not in PROFILES:
        raise ValueError(f"unknown profile {profile!r}; choose from {PROFILES}")
    docs: list[dict] = [
        notebook_crd(conversion_webhook=profile != "standalone"),
        warmpool_crd(),
        rbac_role(),
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": "notebook-controller-sa"},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "notebook-controller-binding"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "notebook-controller-role",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": "notebook-controller-sa",
                    "namespace": "$(NAMESPACE)",
                }
            ],
        },
        params_configmap(profile),
        manager_deployment(profile, image=image),
    ]
    if profile != "standalone":
        docs.extend(webhook_manifests())
    return docs


def render_yaml(profile: str = "standalone",
                image: str = "kubeflow-tpu-controller:latest") -> str:
    return yaml.safe_dump_all(render_profile(profile, image=image),
                              sort_keys=False)


def validate_docs(docs: Iterable[dict]) -> None:
    """CI-style sanity (reference ci/kustomize.sh analog): every doc has
    apiVersion/kind/metadata.name, no duplicate identities."""
    seen = set()
    for doc in docs:
        for key in ("apiVersion", "kind"):
            if not doc.get(key):
                raise ValueError(f"manifest missing {key}: {doc}")
        name = doc.get("metadata", {}).get("name")
        if not name:
            raise ValueError(f"manifest missing metadata.name: {doc.get('kind')}")
        identity = (doc["kind"], name)
        if identity in seen:
            raise ValueError(f"duplicate manifest {identity}")
        seen.add(identity)
