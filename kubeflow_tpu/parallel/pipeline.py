"""Pipeline parallelism: GPipe over a "pipeline" mesh axis.

TPU-first design: the decoder's stacked layer parameters (leading "layers"
axis from `nn.scan`) are sharded across pipeline stages — rule
("layers", "pipeline"), see parallel.sharding.rules_for_mesh — and the
schedule runs under a PARTIALLY-manual `jax.shard_map`: only the pipeline
axis is manual (explicit `lax.ppermute` moves activations stage->stage over
ICI neighbors), while data/fsdp/sequence/tensor stay automatic so the
layers' internal logical sharding constraints keep composing.  pp therefore
stacks with dp/fsdp/sp/tp in one jitted step.

Schedule: classic GPipe.  The global batch splits into M microbatches; for
T = M + S - 1 ticks every stage applies its L/S layers to the activation it
holds and rotates the result to the next stage.  Stage s computes microbatch
m at tick t = s + m; ticks outside that window are bubbles (computed but
masked — uniform control flow keeps the collective schedule identical on
every shard, as ring attention does).  The backward schedule is whatever AD
produces for the scan (activations for all T ticks are live unless
`remat_layer` wraps the layer), so this is throughput-optimal in FLOPs but
not 1F1B-optimal in memory — the standard GPipe trade.

The reference has no analog (single-pod notebooks, SURVEY.md §2.5); this is
part of the in-notebook compute plane the TPU build adds.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

PIPELINE_AXIS = "pipeline"


def num_stages(mesh: Mesh, axis_name: str = PIPELINE_AXIS) -> int:
    return int(mesh.shape.get(axis_name, 1))


def gpipe(
    apply_layer: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = PIPELINE_AXIS,
    remat_layer: bool = False,
    remat_policy=None,
    layer_has_aux: bool = False,
) -> jax.Array:
    """Run a layer stack as a GPipe pipeline.

    apply_layer(layer_params, x) applies ONE layer (params without the
    leading stack axis) to activations x of shape [mb, ...]; the engine
    scans it over each stage's local layers.  stacked_params is the full
    pytree with leading axis L (L % stages == 0), sharded over `axis_name`.
    x: [B, ...] with B % num_microbatches == 0.  Returns [B, ...] outputs,
    replicated over the pipeline axis; with layer_has_aux=True,
    apply_layer returns (x, aux_scalar) per layer (MoE load-balance loss)
    and gpipe returns (out, aux) where aux is the microbatch-mean total —
    per-stage aux is accumulated only over VALID ticks (bubbles compute
    masked garbage) and averaged over microbatches.  Note the estimator
    choice: the load-balance statistic is computed PER MICROBATCH and
    averaged (mean of per-group f·P products), not over the global batch
    (product of global means) — the same per-group convention
    GShard/Mesh-TF use for per-shard batches; both estimators share the
    uniform-routing minimizer.

    Composition constraint: if the stage body itself shards the batch
    dimension (ring attention's shard_map over data/fsdp does), the
    per-microbatch batch B/num_microbatches must remain divisible by that
    sharding group — pick num_microbatches accordingly (e.g.
    B // (data*fsdp)).
    """
    def scan_layers(layer_fn, params, x_in):
        """Scan `layer_fn` over stacked layer params, accumulating the
        per-layer aux into the carry (shared by the single-stage fallback
        and each pipeline stage)."""
        def body(carry, layer_params):
            x, aux = carry
            if layer_has_aux:
                x, layer_aux = layer_fn(layer_params, x)
                return (x, aux + layer_aux), None
            return (layer_fn(layer_params, x), aux), None
        aux0 = jnp.float32(0.0)
        # inside a pipeline stage the aux joins a carry varying over the
        # manual axis; match VMA types (see the pvary note below)
        vma = tuple(getattr(jax.typeof(x_in), "vma", ()))
        if vma:
            aux0 = jax.lax.pvary(aux0, vma)
        (out, aux), _ = jax.lax.scan(body, (x_in, aux0), params)
        return out, aux

    stages = num_stages(mesh, axis_name)
    if stages <= 1:
        out, aux = scan_layers(apply_layer, stacked_params, x)
        return (out, aux) if layer_has_aux else out

    layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if layers % stages != 0:
        raise ValueError(f"{layers} layers not divisible by {stages} stages")
    batch = x.shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by {num_microbatches} microbatches")

    one_layer = apply_layer
    if remat_layer:
        one_layer = jax.checkpoint(apply_layer, policy=remat_policy)

    m_shape = (num_microbatches, batch // num_microbatches) + x.shape[1:]

    def body(stage_params, x_all):
        # stage_params: this stage's [L/stages, ...] slice; x_all: [M, mb, ...]
        s = jax.lax.axis_index(axis_name)
        microbatches = x_all.shape[0]
        ticks = microbatches + stages - 1
        perm = [(i, (i + 1) % stages) for i in range(stages)]

        def apply_stage(x_in):
            return scan_layers(one_layer, stage_params, x_in)

        # pvary: the zero inits join a carry whose other leg (y, rotated
        # activations) varies over the pipeline axis — consistent VMA types
        # let check_vma=True verify the collective placement statically
        # (the safeguard that caught the ring-under-pipeline gradient bug)
        buf = jax.lax.pvary(jnp.zeros_like(x_all[0]), (axis_name,))
        out = jax.lax.pvary(jnp.zeros_like(x_all), (axis_name,))
        aux_acc = jax.lax.pvary(jnp.float32(0.0), (axis_name,))

        def tick(carry, t):
            buf, out, aux_acc = carry
            inject = x_all[jnp.clip(t, 0, microbatches - 1)]
            x_in = jnp.where(s == 0, inject, buf)
            y, aux_t = apply_stage(x_in)
            # this stage works on microbatch m = t - s; bubbles (invalid m)
            # compute masked garbage whose aux must not accumulate
            valid = (t >= s) & (t < s + microbatches)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            m = t - (stages - 1)
            write = out.at[jnp.clip(m, 0, microbatches - 1)].set(y)
            out = jnp.where((s == stages - 1) & (m >= 0), write, out)
            buf = jax.lax.ppermute(y, axis_name, perm)
            return (buf, out, aux_acc), None

        (buf, out, aux_acc), _ = jax.lax.scan(
            tick, (buf, out, aux_acc), jnp.arange(ticks))
        # results live on the last stage; zero-elsewhere + psum replicates
        # them across the pipeline (the head/loss runs on every stage)
        out = jnp.where(s == stages - 1, out, jnp.zeros_like(out))
        out = jax.lax.psum(out, axis_name)
        # total aux: every stage contributed its layers' aux for every
        # microbatch exactly once; batch-mean = sum / microbatches
        aux = jax.lax.psum(aux_acc, axis_name) / microbatches
        return out, aux

    run = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=(P(), P()),
        axis_names={axis_name},
        # check_vma=True on THIS outer shard_map trips an sdy
        # manual_computation lowering error when ring attention's (vma-
        # checked) shard_map nests inside; the engine's collective
        # placement is instead pinned dynamically by the SGD parameter-
        # update allclose gates (tests/test_pipeline.py, dryrun_multichip),
        # which hold to ~1e-7 across device counts
        check_vma=False,
    )
    out, aux = run(stacked_params, x.reshape(m_shape))
    out = out.reshape(x.shape)
    return (out, aux) if layer_has_aux else out


__all__ = ["gpipe", "num_stages", "PIPELINE_AXIS"]
