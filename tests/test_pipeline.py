"""Pipeline parallelism: the GPipe engine and its transformer integration.

Correctness bar mirrors the multichip dryrun: a pipelined run must produce
the SAME loss and parameter updates as the single-program path — a schedule
bug, a misrouted microbatch, or a wrong ppermute shows up as a numeric
diff, not a compile error.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.train import setup_training


from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_tpu.parallel.pipeline import gpipe
from kubeflow_tpu.parallel.sharding import rules_for_mesh


def const_opt():
    """Plain constant-lr SGD for update-equivalence checks: the training
    default's warmup starts at lr=0 (zero first update — vacuous
    comparison), and one-step Adam is ~lr*sign(grad), so fp32 noise on
    near-zero gradients flips signs into 2*lr param diffs; under SGD the
    parameter delta is proportional to the gradient."""
    return optax.sgd(0.05)


class TestGpipeEngine:
    def _ref(self, params, x):
        def body(c, w):
            return jnp.tanh(c @ w), None
        out, _ = jax.lax.scan(body, x, params)
        return out

    def test_forward_and_grad_match_sequential(self):
        mesh = make_mesh(MeshConfig(data=2, pipeline=4))
        layers, dim, batch = 8, 16, 8
        params = jax.random.normal(jax.random.PRNGKey(0),
                                   (layers, dim, dim)) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, dim))

        def apply_one(w, xb):
            return jnp.tanh(xb @ w)

        got = jax.jit(lambda p, xb: gpipe(apply_one, p, xb, mesh, 4))(params, x)
        ref = self._ref(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)

        g1 = jax.jit(jax.grad(
            lambda p: jnp.sum(gpipe(apply_one, p, x, mesh, 4) ** 2)))(params)
        g2 = jax.grad(lambda p: jnp.sum(self._ref(p, x) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)

    def test_single_stage_is_plain_scan(self):
        mesh = make_mesh(MeshConfig(data=8))
        params = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8)) * 0.1
        x = jnp.ones((4, 8))
        got = gpipe(lambda w, xb: jnp.tanh(xb @ w), params, x, mesh, 2)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(self._ref(params, x)), atol=1e-6)

    def test_rejects_indivisible(self):
        mesh = make_mesh(MeshConfig(data=2, pipeline=4))
        params = jnp.zeros((6, 4, 4))  # 6 layers % 4 stages != 0
        with pytest.raises(ValueError, match="not divisible"):
            gpipe(lambda w, x: x, params, jnp.ones((4, 4)), mesh, 2)
        params = jnp.zeros((8, 4, 4))
        with pytest.raises(ValueError, match="microbatch"):
            gpipe(lambda w, x: x, params, jnp.ones((3, 4)), mesh, 2)


class TestPipelinedTraining:
    def test_rules_shard_layers_over_pipeline(self):
        mesh = make_mesh(MeshConfig(data=2, pipeline=4))
        rules = dict(rules_for_mesh(mesh))
        assert rules["layers"] == "pipeline"
        flat = dict(rules_for_mesh(make_mesh(MeshConfig(data=8))))
        assert flat["layers"] is None

    def test_pipelined_step_matches_single_program(self):
        """Full train step: pp=2 (+dp) must reproduce the plain run's loss
        and parameter updates on the same batch."""
        cfg = TINY  # 2 layers -> 2 stages
        batch_shape = (8, 64)
        data = {
            "inputs": jax.random.randint(jax.random.PRNGKey(3), batch_shape,
                                         0, cfg.vocab_size),
        }
        data["targets"] = jnp.roll(data["inputs"], -1, axis=1)

        plain_mesh = make_mesh(MeshConfig(data=1),
                               devices=jax.devices()[:1])
        plain = setup_training(cfg, plain_mesh, batch_shape=batch_shape,
                               optimizer=const_opt())
        # host copy BEFORE the step: train_step donates the input state
        init_leaf = np.asarray(
            jax.device_get(jax.tree_util.tree_leaves(plain.state.params)[0]))
        plain_state, plain_metrics = plain.train_step(plain.state, data)

        pp_mesh = make_mesh(MeshConfig(data=-1, pipeline=2))
        pp = setup_training(cfg, pp_mesh, batch_shape=batch_shape,
                            pipeline_microbatches=4, optimizer=const_opt())
        pp_state, pp_metrics = pp.train_step(pp.state, data)

        # the comparison must not be vacuous: the step moved the weights
        new_leaf = np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(plain_state.params)[0]))
        assert float(np.max(np.abs(new_leaf - init_leaf))) > 0.0

        assert abs(float(pp_metrics["loss"]) -
                   float(plain_metrics["loss"])) < 1e-4
        ref = jax.device_get(plain_state.params)
        got = jax.device_get(pp_state.params)
        mismatch = []

        def cmp(path, a, b):
            if not np.allclose(a, b, rtol=1e-4, atol=1e-4):
                mismatch.append(jax.tree_util.keystr(path))

        jax.tree_util.tree_map_with_path(cmp, ref, got)
        assert not mismatch, mismatch

    def test_pipeline_with_chunked_loss(self):
        cfg = TINY.with_(loss_chunks=4)
        mesh = make_mesh(MeshConfig(data=-1, pipeline=2))
        setup = setup_training(cfg, mesh, batch_shape=(4, 64),
                               pipeline_microbatches=2)
        data = {"inputs": jnp.ones((4, 64), jnp.int32),
                "targets": jnp.ones((4, 64), jnp.int32)}
        _, metrics = setup.train_step(setup.state, data)
        assert 0 < float(metrics["loss"]) < 20


class Test1F1B:
    """The 1F1B engine (parallel.pipeline.pipeline_1f1b) holds the same
    correctness bar as GPipe — identical parameter updates to the
    single-program run — while capping the activation stash at `stages`
    microbatches instead of all ticks."""

    def _data(self, cfg, batch_shape, seed=3):
        data = {"inputs": jax.random.randint(jax.random.PRNGKey(seed),
                                             batch_shape, 0, cfg.vocab_size)}
        data["targets"] = jnp.roll(data["inputs"], -1, axis=1)
        return data

    def _param_allclose(self, ref_state, got_state):
        mismatch = []

        def cmp(path, a, b):
            if not np.allclose(a, b, rtol=1e-4, atol=1e-4):
                mismatch.append(jax.tree_util.keystr(path))

        jax.tree_util.tree_map_with_path(
            cmp, jax.device_get(ref_state.params),
            jax.device_get(got_state.params))
        assert not mismatch, mismatch

    def test_1f1b_matches_single_program(self):
        cfg = TINY
        batch_shape = (8, 64)
        data = self._data(cfg, batch_shape)
        plain = setup_training(
            cfg, make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]),
            batch_shape=batch_shape, optimizer=const_opt())
        plain_state, plain_metrics = plain.train_step(plain.state, data)

        pp = setup_training(cfg, make_mesh(MeshConfig(data=-1, pipeline=2)),
                            batch_shape=batch_shape, pipeline_microbatches=4,
                            optimizer=const_opt(), pipeline_schedule="1f1b")
        pp_state, pp_metrics = pp.train_step(pp.state, data)

        assert abs(float(pp_metrics["loss"]) -
                   float(plain_metrics["loss"])) < 1e-4
        self._param_allclose(plain_state, pp_state)

    def test_1f1b_moe_matches_single_program(self):
        """MoE composes: the aux loss and its gradient flow through the
        in-schedule vjp (per-microbatch aux estimator, the same GShard
        convention gpipe documents — params must still match)."""
        cfg = TINY.with_(moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0)
        batch_shape = (8, 64)
        data = self._data(cfg, batch_shape)
        plain = setup_training(
            cfg, make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]),
            batch_shape=batch_shape, optimizer=const_opt())
        plain_state, _ = plain.train_step(plain.state, data)
        pp = setup_training(cfg, make_mesh(MeshConfig(data=-1, pipeline=2)),
                            batch_shape=batch_shape, pipeline_microbatches=4,
                            optimizer=const_opt(), pipeline_schedule="1f1b")
        pp_state, _ = pp.train_step(pp.state, data)
        self._param_allclose(plain_state, pp_state)

    def test_1f1b_lower_peak_memory_than_gpipe(self):
        """The schedule's point: at pp=4 with 16 microbatches the compiled
        per-device temp allocation must be measurably below gpipe's
        (activation stash S vs M+S-1 ticks)."""
        cfg = TINY.with_(num_layers=8, embed_dim=128, mlp_dim=256,
                         max_seq_len=256)
        bs = (32, 256)
        data = {"inputs": jnp.ones(bs, jnp.int32),
                "targets": jnp.ones(bs, jnp.int32)}
        temps = {}
        for sched in ("gpipe", "1f1b"):
            mesh = make_mesh(MeshConfig(data=-1, pipeline=4))
            s = setup_training(cfg, mesh, batch_shape=bs,
                               pipeline_microbatches=16,
                               optimizer=const_opt(),
                               pipeline_schedule=sched)
            ma = s.train_step.lower(s.state, data).compile().memory_analysis()
            temps[sched] = ma.temp_size_in_bytes
        assert temps["1f1b"] < 0.8 * temps["gpipe"], temps

    def test_1f1b_rejects_single_stage(self):
        from kubeflow_tpu.parallel.pipeline import pipeline_1f1b

        mesh = make_mesh(MeshConfig(data=8))
        with pytest.raises(ValueError, match="pipeline axis"):
            pipeline_1f1b(lambda w, x: x, jnp.zeros((2, 4, 4)),
                          lambda hp, y, t: jnp.float32(0.0), {},
                          jnp.ones((4, 4)), jnp.ones((4, 4), jnp.int32),
                          mesh, 2)
