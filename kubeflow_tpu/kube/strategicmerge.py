"""Strategic merge patch (application/strategic-merge-patch+json).

Kubernetes' strategic merge differs from RFC 7386 in one load-bearing way:
lists of objects whose Go type carries a `patchMergeKey` tag are merged
BY KEY (containers by name, volumeMounts by mountPath, ...) instead of
replaced wholesale, and patches can carry directives (`$patch: delete`,
`$patch: replace`, `$deleteFromPrimitiveList/...`, `$retainKeys`).  The
apiserver reads the merge keys from struct tags (k8s.io/api/core/v1/
types.go); a dynamic server has no structs, so we pin the well-known keys
the workload API actually uses — the same table kubectl's openapi-less
fallback hardcodes.

Reference context: the reference's controllers send merge patches
(odh notebook_controller.go:516-523) but kubectl apply against the CRD
sends strategic-merge for core types; serving it faithfully keeps the
wire server honest as an envtest analog (docs/wire_compat.md).
"""

from __future__ import annotations

import copy
from typing import Any

# field name -> candidate merge keys, tried in order; first key present on
# every object item wins.  Candidates resolve same-named fields with
# different keys (Container.ports keys on containerPort, ServiceSpec.ports
# on port).  Mirrors the patchMergeKey struct tags in k8s.io/api.
MERGE_KEYS: dict[str, tuple[str, ...]] = {
    "containers": ("name",),
    "initContainers": ("name",),
    "ephemeralContainers": ("name",),
    "env": ("name",),
    "ports": ("containerPort", "port"),
    "volumeMounts": ("mountPath",),
    "volumeDevices": ("devicePath",),
    "volumes": ("name",),
    "imagePullSecrets": ("name",),
    "hostAliases": ("ip",),
    "topologySpreadConstraints": ("topologyKey",),
    "readinessGates": ("conditionType",),
    "conditions": ("type",),
    "secrets": ("name",),          # ServiceAccount.secrets
    "ownerReferences": ("uid",),   # ObjectMeta.ownerReferences
}

# primitive lists with patchStrategy=merge: patch items UNION into the base
# list (ObjectMeta.finalizers); everything else replaces atomically
PRIMITIVE_MERGE_FIELDS = frozenset({"finalizers"})

_DELETE_PRIMITIVE = "$deleteFromPrimitiveList/"
_SET_ORDER = "$setElementOrder/"


def strategic_merge(base: dict, patch: dict) -> dict:
    """Apply `patch` to `base` with strategic-merge semantics.  Neither
    input is mutated (the one deep copy happens here; the recursive helpers
    build in place).  `$setElementOrder` directives are accepted and
    ignored (ordering hints only — the merged content is unaffected)."""
    return _merge_map(copy.deepcopy(base), patch)


def _is_directive(key: str) -> bool:
    return (key in ("$patch", "$retainKeys")
            or key.startswith(_DELETE_PRIMITIVE)
            or key.startswith(_SET_ORDER))


def _is_pure_directive(item: Any) -> bool:
    return (isinstance(item, dict) and bool(item)
            and all(_is_directive(k) for k in item))


def _clean(val: Any) -> Any:
    """Deep-copy with every $-directive stripped — directives drive the
    merge; they must never be persisted (the apiserver strips them too)."""
    if isinstance(val, dict):
        return {k: _clean(v) for k, v in val.items() if not _is_directive(k)}
    if isinstance(val, list):
        return [_clean(x) for x in val if not _is_pure_directive(x)]
    return copy.deepcopy(val)


def _merge_map(out: dict, patch: dict) -> dict:
    """Merge `patch` into `out` IN PLACE (out is owned by the caller's one
    deep copy) and return it."""
    if patch.get("$patch") == "replace":
        return _clean(patch)
    if patch.get("$patch") == "delete":
        return {}
    for key, val in patch.items():
        if _is_directive(key):
            continue  # directive passes run after field merges
        if val is None or (isinstance(val, dict)
                           and val.get("$patch") == "delete"):
            out.pop(key, None)
        elif isinstance(val, dict) and isinstance(out.get(key), dict):
            out[key] = _merge_map(out[key], val)
        elif isinstance(val, list) and key in MERGE_KEYS:
            cur = out.get(key)
            out[key] = _merge_list(cur if isinstance(cur, list) else [],
                                   val, MERGE_KEYS[key])
        elif (isinstance(val, list) and key in PRIMITIVE_MERGE_FIELDS
              and isinstance(out.get(key), list)):
            out[key] = out[key] + [x for x in val if x not in out[key]]
        else:
            out[key] = _clean(val)
    # deletions LAST, independent of JSON key order — kubectl emits
    # additions and $deleteFromPrimitiveList for the same field in one patch
    for key, val in patch.items():
        if key.startswith(_DELETE_PRIMITIVE):
            field = key[len(_DELETE_PRIMITIVE):]
            cur = out.get(field)
            if isinstance(cur, list) and isinstance(val, list):
                out[field] = [x for x in cur if x not in val]
    # $retainKeys (patchStrategy=retainKeys): after the merge, the map keeps
    # only the listed keys — kubectl uses it to clear one-of fields
    retain = patch.get("$retainKeys")
    if isinstance(retain, list):
        for key in [k for k in out if k not in retain]:
            out.pop(key)
    return out


def _pick_key(base: list, patch: list, candidates: tuple[str, ...]):
    """First candidate key present on every dict item (pure-directive items
    like {"$patch": "replace"} don't vote); None -> the list is treated
    atomically.  If the BASE items agree on a merge key but a patch item
    omits it, the patch is malformed — raise rather than silently degrade
    to whole-list replace (the apiserver answers 'does not contain declared
    merge key')."""
    if any(not isinstance(x, dict) for x in list(base) + list(patch)):
        return None
    voting = [x for x in list(base) + list(patch) if not _is_pure_directive(x)]
    if not voting:
        return None
    for cand in candidates:
        if all(cand in x for x in voting):
            return cand
    base_voting = [x for x in base if not _is_pure_directive(x)]
    for cand in candidates:
        if base_voting and all(cand in x for x in base_voting):
            raise ValueError(
                f"strategic merge patch list item does not contain the "
                f"declared merge key {cand!r}")
    return None


def _merge_list(out: list, patch: list, candidates: tuple[str, ...]) -> list:
    """Merge `patch` into `out` IN PLACE (caller owns the copy); returns
    the merged list."""
    # an {"$patch": "replace"} item means: the patch list (minus the
    # directive) replaces the base list entirely
    if any(isinstance(x, dict) and x.get("$patch") == "replace" for x in patch):
        return _clean([x for x in patch
                       if not (isinstance(x, dict)
                               and x.get("$patch") == "replace")])
    key = _pick_key(out, patch, candidates)
    if key is None:
        return _clean(patch)
    for item in patch:
        if _is_pure_directive(item):
            if item.get("$patch") == "delete":
                out.clear()  # a key-less delete directive clears the list
            continue  # other pure directives never become items
        if not isinstance(item, dict) or key not in item:
            out.append(_clean(item))
            continue
        if item.get("$patch") == "delete":
            out[:] = [x for x in out
                      if not (isinstance(x, dict) and x.get(key) == item[key])]
            continue
        for i, existing in enumerate(out):
            if isinstance(existing, dict) and existing.get(key) == item[key]:
                out[i] = _merge_map(existing, item)
                break
        else:
            out.append(_clean(item))
    return out
