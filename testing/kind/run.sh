#!/usr/bin/env bash
# One-command self-recording kind lane: every step of README.md executed
# in order, with the evidence the README asks for captured MECHANICALLY —
# the first docker-bearing environment that runs this produces the
# committable artifact with zero judgment at run time:
#   testing/kind/RUN_<date>.log      full transcript (fixtures --real,
#                                    deploy, behavioral runner PASS lines)
#   testing/kind/RUN_<date>.nodes.json   per-node allocatable (google.com/tpu)
# A failure anywhere still leaves the partial log for diagnosis (the trap
# records the exit code as the last line).
set -euo pipefail
cd "$(dirname "$0")/../.."

STAMP=$(date +%Y-%m-%d_%H%M%S)
LOG="testing/kind/RUN_${STAMP}.log"
NODES="testing/kind/RUN_${STAMP}.nodes.json"
CLUSTER="${CLUSTER:-kubeflow-tpu}"
PROXY_PORT="${PROXY_PORT:-8001}"

exec > >(tee "$LOG") 2>&1
finish() {
  rc=$?
  echo "== exit code: $rc =="
  [[ -n "${PROXY_PID:-}" ]] && kill "$PROXY_PID" 2>/dev/null || true
  exit $rc
}
trap finish EXIT

echo "== kind lane run ${STAMP} =="
command -v docker >/dev/null || { echo "no docker in this environment"; exit 2; }
bash testing/kind/install_kind.sh
kind get clusters | grep -qx "$CLUSTER" || \
  kind create cluster --name "$CLUSTER" --wait 120s \
    --config testing/kind/cluster.yaml

kubectl proxy --port "$PROXY_PORT" &
PROXY_PID=$!
sleep 2

echo "== 1/3 apiserver fixtures against the REAL apiserver =="
# CRD without the conversion clause first: fixtures run pre-controller
python - <<'PY' | kubectl apply -f -
import yaml
from kubeflow_tpu.deploy.manifests import notebook_crd
print(yaml.safe_dump(notebook_crd(conversion_webhook=False)))
PY
python -m kubeflow_tpu.kube.fixtures \
  --server "http://127.0.0.1:${PROXY_PORT}" --real

echo "== 2/3 webhook-enabled deploy + fake TPU device plugin =="
bash testing/kind/deploy.sh

echo "== capturing node allocatable -> ${NODES} =="
kubectl get nodes -o json | python -c '
import json, sys
items = json.load(sys.stdin)["items"]
out = [{"name": n["metadata"]["name"],
        "allocatable": n["status"]["allocatable"]} for n in items]
print(json.dumps(out, indent=2))
' > "$NODES"
cat "$NODES"

echo "== 3/3 black-box behavioral contract (gang must BIND) =="
python conformance/behavior.py \
  --server "http://127.0.0.1:${PROXY_PORT}" --expect-scheduled

echo "kind lane: PASS (evidence: ${LOG}, ${NODES})"
