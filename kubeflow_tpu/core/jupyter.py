"""Jupyter server activity probing.

The reference talks plain HTTP to the Jupyter REST API through the notebook
Service DNS (culling_controller.go:244-322):
GET http://{name}.{ns}.svc.{domain}/notebook/{ns}/{name}/api/kernels and
/api/terminals, 10s timeout, 1MiB body cap, nil on non-200 or bad JSON.

The transport is a protocol so the culling controller is testable without a
network (the fake holds per-notebook kernel/terminal state) and so a future
gRPC/ipc activity channel (e.g. a TPU MFU heartbeat) can slot in."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional, Protocol

PROBE_TIMEOUT_S = 10.0
BODY_LIMIT = 1 << 20


class JupyterAPI(Protocol):
    def get_kernels(self, name: str, namespace: str) -> Optional[list[dict]]: ...
    def get_terminals(self, name: str, namespace: str) -> Optional[list[dict]]: ...


class HttpJupyterClient:
    """Production transport (getNotebookResourceResponse, :244-274): in-cluster
    Service DNS, or the kubectl proxy path under DEV."""

    def __init__(self, cluster_domain: str = "cluster.local", dev: bool = False,
                 base_url: str = ""):
        self.cluster_domain = cluster_domain
        self.dev = dev
        # base_url overrides host resolution (a third transport next to
        # in-cluster DNS and the DEV kubectl-proxy path): tests and
        # port-forward setups point it at a concrete host:port while keeping
        # the /notebook/{ns}/{name} path contract
        self.base_url = base_url.rstrip("/")

    def _url(self, name: str, namespace: str, resource: str) -> str:
        if self.base_url:
            return (f"{self.base_url}/notebook/{namespace}/{name}"
                    f"/api/{resource}")
        if self.dev:
            # port name must match generate_service's "http-notebook" (the
            # reference's dev path addresses "http-{name}", which only works
            # for a notebook literally named "notebook" — fixed here)
            return (
                f"http://localhost:8001/api/v1/namespaces/{namespace}/services/"
                f"{name}:http-notebook/proxy/notebook/{namespace}/{name}/api/{resource}"
            )
        return (
            f"http://{name}.{namespace}.svc.{self.cluster_domain}"
            f"/notebook/{namespace}/{name}/api/{resource}"
        )

    def _get(self, name: str, namespace: str, resource: str) -> Optional[list[dict]]:
        url = self._url(name, namespace, resource)
        try:
            with urllib.request.urlopen(url, timeout=PROBE_TIMEOUT_S) as resp:
                if resp.status != 200:
                    return None
                body = resp.read(BODY_LIMIT)
        except (urllib.error.URLError, OSError, ValueError):
            return None
        try:
            data = json.loads(body)
        except ValueError:
            return None
        return data if isinstance(data, list) else None

    def get_kernels(self, name: str, namespace: str) -> Optional[list[dict]]:
        return self._get(name, namespace, "kernels")

    def get_terminals(self, name: str, namespace: str) -> Optional[list[dict]]:
        return self._get(name, namespace, "terminals")


class FakeJupyterState:
    """Test/standalone transport: per-notebook kernel and terminal state.

    kernels entries: {"id", "name", "last_activity", "execution_state",
    "connections"}; terminals: {"name", "last_activity"} — the shapes the
    Jupyter API returns (KernelStatus/TerminalStatus,
    culling_controller.go:63-85)."""

    def __init__(self) -> None:
        self._kernels: dict[tuple[str, str], Optional[list[dict]]] = {}
        self._terminals: dict[tuple[str, str], Optional[list[dict]]] = {}

    def set_kernels(
        self, namespace: str, name: str, kernels: Optional[list[dict]]
    ) -> None:
        self._kernels[(namespace, name)] = kernels

    def set_terminals(
        self, namespace: str, name: str, terminals: Optional[list[dict]]
    ) -> None:
        self._terminals[(namespace, name)] = terminals

    def get_kernels(self, name: str, namespace: str) -> Optional[list[dict]]:
        return self._kernels.get((namespace, name))

    def get_terminals(self, name: str, namespace: str) -> Optional[list[dict]]:
        return self._terminals.get((namespace, name))
