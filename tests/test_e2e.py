"""e2e phase harness: create -> validate -> update -> delete over a fixture
matrix, the analog of the reference's real-cluster suite
(odh e2e/notebook_controller_setup_test.go:55-120: notebookContext list,
phased TestE2ENotebookController, poll-until helpers) run against the full
in-memory stack with the threaded manager — the closest thing to a cluster
this environment has.
"""

import time
from dataclasses import dataclass, field
from typing import Optional

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core import constants as CC
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.odh import constants as OC
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

CENTRAL_NS = "opendatahub"
# generous, like the reference's 3-minute e2e resource timeout
# (notebook_controller_setup_test.go:94): a full-suite run shares the host
# with compile-heavy compute tests, and a starved reconcile thread must
# show up as slow, not as a phase flake
POLL_TIMEOUT_S = 60.0
POLL_INTERVAL_S = 0.02


@dataclass
class NotebookContext:
    """One e2e fixture (reference notebookContext, setup_test.go:55-61)."""

    name: str
    tpu: Optional[TPUSpec] = None
    annotations: dict = field(default_factory=dict)
    namespace: str = "e2e"

    @property
    def expected_hosts(self) -> int:
        return (self.tpu.shape.num_hosts * self.tpu.slices) if self.tpu else 1

    @property
    def auth(self) -> bool:
        return self.annotations.get(OC.ANNOTATION_INJECT_AUTH) == "true"


CONTEXTS = [
    NotebookContext("e2e-cpu"),
    NotebookContext("e2e-tpu-1chip", tpu=TPUSpec("v5e", "1x1")),
    NotebookContext("e2e-tpu-multihost", tpu=TPUSpec("v5e", "4x4")),
    NotebookContext(
        "e2e-tpu-multislice", tpu=TPUSpec("v5e", "4x4", slices=2)
    ),
    NotebookContext(
        "e2e-tpu-auth",
        tpu=TPUSpec("v5e", "2x4"),
        annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
    ),
]


def wait_for(cond, what: str):
    """PollUntilContextTimeout analog (e2e helper_test.go:28-56)."""
    deadline = time.time() + POLL_TIMEOUT_S
    while time.time() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(POLL_INTERVAL_S)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def stack():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "256", "memory": "1024Gi"})
    # enough TPU capacity for every fixture simultaneously
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 16, 4, "v5e-4x4")
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "1x1", 2, 1, "v5e-1x1")
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "2x4", 4, 8, "v5e-2x4")
    mgr = Manager(api)
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, OdhConfig(controller_namespace=CENTRAL_NS))
    mgr.start()
    yield api, cluster, mgr
    mgr.stop()


@pytest.mark.parametrize("ctx", CONTEXTS, ids=lambda c: c.name)
class TestE2ENotebookLifecycle:
    def test_phase_create(self, stack, ctx):
        api, _, _ = stack
        api.create(
            Notebook.new(
                ctx.name, ctx.namespace, tpu=ctx.tpu, annotations=ctx.annotations
            ).obj
        )
        wait_for(
            lambda: (nb := api.try_get("Notebook", ctx.namespace, ctx.name))
            is not None
            and OC.STOP_ANNOTATION not in nb.metadata.annotations,
            f"{ctx.name}: reconciliation lock removed",
        )
        wait_for(
            lambda: (nb := api.try_get("Notebook", ctx.namespace, ctx.name))
            is not None
            and nb.body.get("status", {}).get("readyReplicas")
            == ctx.expected_hosts,
            f"{ctx.name}: {ctx.expected_hosts} ready workers",
        )

    def test_phase_validate(self, stack, ctx):
        api, _, _ = stack
        # workload objects
        num_slices = ctx.tpu.slices if ctx.tpu else 1
        for s in range(num_slices):
            sts_name = (
                ctx.name if num_slices == 1 else f"{ctx.name}-slice-{s}"
            )
            sts = api.get("StatefulSet", ctx.namespace, sts_name)
            per_slice = ctx.tpu.shape.num_hosts if ctx.tpu else 1
            assert sts.spec["replicas"] == per_slice
        assert api.try_get("Service", ctx.namespace, ctx.name) is not None
        if ctx.tpu:
            headless = api.get("Service", ctx.namespace, f"{ctx.name}-workers")
            assert headless.spec["clusterIP"] == "None"
            status = api.get("Notebook", ctx.namespace, ctx.name).body["status"]
            assert status["sliceHealth"] == "Healthy"
            assert len(status["workerStates"]) == ctx.expected_hosts
            # distributed env on a worker pod
            sts0 = ctx.name if num_slices == 1 else f"{ctx.name}-slice-0"
            pod = api.get("Pod", ctx.namespace, f"{sts0}-0")
            env = {e["name"] for e in pod.spec["containers"][0]["env"]}
            assert {"TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES",
                    "JAX_COORDINATOR_ADDRESS"} <= env
            if num_slices > 1:
                assert "MEGASCALE_NUM_SLICES" in env
        # routing
        routes = api.list(
            "HTTPRoute", namespace=CENTRAL_NS,
            label_selector={"notebook-name": ctx.name},
        )
        assert len(routes) == 1
        backend = routes[0].spec["rules"][0]["backendRefs"][0]
        assert backend["port"] == (8443 if ctx.auth else 8888)
        assert (
            api.try_get("ReferenceGrant", ctx.namespace, OC.REFERENCEGRANT_NAME)
            is not None
        )
        # network policies
        assert api.try_get(
            "NetworkPolicy", ctx.namespace, f"{ctx.name}-ctrl-np"
        ) is not None
        if ctx.auth:
            assert api.try_get("ServiceAccount", ctx.namespace, ctx.name) is not None
            pod_containers = api.get(
                "Pod", ctx.namespace,
                f"{ctx.name if (not ctx.tpu or ctx.tpu.slices == 1) else ctx.name + '-slice-0'}-0",
            ).spec["containers"]
            assert any(c["name"] == "kube-rbac-proxy" for c in pod_containers)

    def test_phase_update_stop_resume(self, stack, ctx):
        api, _, _ = stack
        live = api.get("Notebook", ctx.namespace, ctx.name)
        live.metadata.annotations[CC.STOP_ANNOTATION] = "2026-07-29T00:00:00Z"
        api.update(live)
        wait_for(
            lambda: all(
                s.spec["replicas"] == 0
                for s in api.list("StatefulSet", namespace=ctx.namespace)
                if s.metadata.labels.get("notebook-name", s.name) == ctx.name
                or s.name == ctx.name
            ),
            f"{ctx.name}: slice-atomic stop",
        )
        live = api.get("Notebook", ctx.namespace, ctx.name)
        del live.metadata.annotations[CC.STOP_ANNOTATION]
        api.update(live)
        wait_for(
            lambda: api.get("Notebook", ctx.namespace, ctx.name)
            .body.get("status", {})
            .get("readyReplicas")
            == ctx.expected_hosts,
            f"{ctx.name}: resume",
        )

    def test_phase_delete(self, stack, ctx):
        api, _, _ = stack
        api.delete("Notebook", ctx.namespace, ctx.name)
        wait_for(
            lambda: api.try_get("Notebook", ctx.namespace, ctx.name) is None,
            f"{ctx.name}: finalized",
        )
        wait_for(
            lambda: not api.list(
                "HTTPRoute", namespace=CENTRAL_NS,
                label_selector={"notebook-name": ctx.name},
            ),
            f"{ctx.name}: route cleanup",
        )
        # polled like every other phase check: a reconcile that raced the
        # cascade may briefly recreate a slice STS; the store's dangling-
        # owner GC (kube/store.py _collect_dangling_owners) must reap it
        wait_for(
            lambda: not [
                s for s in api.list("StatefulSet", namespace=ctx.namespace)
                if s.name.startswith(ctx.name)
            ],
            f"{ctx.name}: owned StatefulSets garbage-collected",
        )
