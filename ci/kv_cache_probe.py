"""KV-cache mechanics probe: is the in-loop dynamic_update_slice in
place, and how fast does the decode einsum actually read the cache?

Three scan bodies over the 470M decode cache shapes (10 layers x K,V of
[16, 12, 384, 128] bf16 = 377 MB total), each 255 iterations inside ONE
jit (relay round-trip amortized):

  update-only   DUS a one-token slab into every buffer.  In place =>
                ~nothing; a copy => read+write 755 MB/iter.
  read-only     the decode attention einsum over every buffer (no DUS):
                the pure read path vs the 819 GB/s spec.
  read+update   both — the real decode step's cache mechanics.

Usage: python ci/kv_cache_probe.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

LAYERS, B, KVH, S, D = 10, 16, 12, 384, 128
ITERS = 255
BYTES = LAYERS * 2 * B * KVH * S * D * 2  # all caches, bf16


def run(name, body):
    caches = [jnp.zeros((B, KVH, S, D), jnp.bfloat16)
              for _ in range(LAYERS * 2)]

    @jax.jit
    def loop(caches):
        def step(carry, i):
            caches, acc = carry
            caches, out = body(caches, i)
            return (caches, acc + out), None

        (caches, acc), _ = jax.lax.scan(
            step, (caches, jnp.float32(0.0)), jnp.arange(ITERS))
        return acc

    np.asarray(loop(caches))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(loop(caches))
        best = min(best, time.perf_counter() - t0)
    per_iter = best / ITERS
    gbps = BYTES / per_iter / 1e9
    print(f"{name:14s} {per_iter * 1e3:7.3f} ms/iter  "
          f"(cache bytes once = {gbps:5.0f} GB/s equivalent)")
    return per_iter


def main():
    slab = jnp.ones((B, KVH, 1, D), jnp.bfloat16)
    q = jnp.ones((B, 1, KVH, 1, D), jnp.bfloat16)  # grouped, G=1 here

    def update_only(caches, i):
        pos = jnp.minimum(i, S - 1)
        caches = [jax.lax.dynamic_update_slice(c, slab, (0, 0, pos, 0))
                  for c in caches]
        return caches, jnp.float32(0.0)

    def read_only(caches, i):
        acc = jnp.float32(0.0)
        for c in caches:
            scores = jnp.einsum("bqkgd,bksd->bkgqs",
                                q, c, preferred_element_type=jnp.float32)
            acc += jnp.sum(scores)
        return caches, acc

    def read_update(caches, i):
        caches, _ = update_only(caches, i)
        return read_only(caches, i)

    run("update-only", update_only)
    run("read-only", read_only)
    run("read+update", read_update)
    ideal = BYTES / 819e9
    print(f"ideal read-once: {ideal * 1e3:.3f} ms/iter @ 819 GB/s")


if __name__ == "__main__":
    main()
