"""Self-signed CA + serving-cert minting for the webhook HTTPS server.

The reference gets webhook TLS from the OpenShift service-CA operator (the
`service.beta.openshift.io/serving-cert-secret-name` annotation on the
webhook Service) and envtest generates local certs for its webhook server
(odh suite_test.go:121-124, WebhookInstallOptions).  This module is the
local analog: a throwaway CA signs a server cert for the given SANs, so
tests and standalone mode can serve real TLS without cluster infrastructure.
Uses the `cryptography` package (baked into the image).
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from dataclasses import dataclass
from typing import Optional

from ..utils.clock import Clock


@dataclass
class CertBundle:
    ca_cert_pem: bytes
    cert_pem: bytes
    key_pem: bytes

    def write(self, cert_dir: str, prefix: str = "tls") -> tuple[str, str, str]:
        """Write tls.crt/tls.key/ca.crt into cert_dir (the layout
        controller-runtime's webhook server expects), returns the paths."""
        os.makedirs(cert_dir, exist_ok=True)
        paths = (
            os.path.join(cert_dir, f"{prefix}.crt"),
            os.path.join(cert_dir, f"{prefix}.key"),
            os.path.join(cert_dir, "ca.crt"),
        )
        for path, data in zip(paths, (self.cert_pem, self.key_pem,
                                      self.ca_cert_pem)):
            with open(path, "wb") as f:
                f.write(data)
        os.chmod(paths[1], 0o600)
        return paths

    def server_ssl_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        # a context needs files on disk; keep them in a private tmpdir
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            cert, key, _ = self.write(d)
            ctx.load_cert_chain(cert, key)
        return ctx

    def client_ssl_context(self) -> ssl.SSLContext:
        import tempfile

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        with tempfile.TemporaryDirectory() as d:
            ca = os.path.join(d, "ca.crt")
            with open(ca, "wb") as f:
                f.write(self.ca_cert_pem)
            ctx.load_verify_locations(ca)
        return ctx


def mint_serving_cert(
    common_name: str = "kubeflow-tpu-webhook",
    dns_names: tuple[str, ...] = ("localhost",),
    ip_addresses: tuple[str, ...] = ("127.0.0.1",),
    valid_days: int = 7,
    clock: Optional[Clock] = None,
) -> CertBundle:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    # injected clock: cert validity anchors to the caller's time source
    # (a real Clock in production; tests can mint from a FakeClock)
    now = datetime.datetime.fromtimestamp(
        (clock or Clock()).now(), datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=valid_days)

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, f"{common_name}-ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now).not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    key = ec.generate_private_key(ec.SECP256R1())
    sans: list[x509.GeneralName] = [x509.DNSName(d) for d in dns_names]
    sans += [x509.IPAddress(ipaddress.ip_address(ip)) for ip in ip_addresses]
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now).not_valid_after(not_after)
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    pem = serialization.Encoding.PEM
    return CertBundle(
        ca_cert_pem=ca_cert.public_bytes(pem),
        cert_pem=cert.public_bytes(pem),
        key_pem=key.private_bytes(
            pem,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )


__all__ = ["CertBundle", "mint_serving_cert"]
