"""Flagship-config decode on one v5e: Llama-2-7B architecture, int8.

BASELINE.md's workload matrix tops out at the 7B configs on multi-chip
slices; this measures what ONE 16-GiB chip does serving the 7B
architecture with int8 weight streaming (models/quant.py — ~6.7 GiB of
kernels instead of 13.5 GiB bf16, leaving room for the KV cache).

Params are materialized host-side leaf by leaf (random weights — decode
throughput does not depend on values) and quantized before device_put,
so no fp32/bf16 full tree ever touches HBM.

Usage: python ci/llama7b_decode.py [batch] [new_tokens]
Prints one JSON line with tok/s and the honest int8+KV roofline.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.configs import LLAMA2_7B  # noqa: E402
from kubeflow_tpu.models.generate import decode_config, generate  # noqa: E402
from kubeflow_tpu.models.quant import quantize_params  # noqa: E402
from kubeflow_tpu.models.transformer import Transformer  # noqa: E402
from kubeflow_tpu.tpu.topology import ACCELERATORS  # noqa: E402


def host_random_params(model, sample, rng=0):
    """Abstract-init the param tree, then materialize each leaf with host
    numpy (normal * 0.02, the init scale class) — never more than one
    leaf's fp32 in memory."""
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), sample)["params"])
    import flax.linen as nn

    abstract = nn.unbox(abstract)
    rs = np.random.RandomState(rng)

    def materialize(leaf):
        arr = (rs.standard_normal(leaf.shape) * 0.02).astype(np.float32)
        return jnp.asarray(arr.astype("bfloat16"))

    return jax.tree.map(materialize, abstract)


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    new_tokens = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    prompt_len = 128
    cfg = decode_config(LLAMA2_7B).with_(
        max_seq_len=prompt_len + new_tokens, weight_dtype="int8")

    model_f = Transformer(decode_config(LLAMA2_7B).with_(
        max_seq_len=prompt_len + new_tokens))
    sample = jnp.ones((1, 8), jnp.int32)
    # host-side init + quantize per leaf: the bf16 tree lives on HOST, the
    # int8 tree on device
    with jax.default_device(jax.devices("cpu")[0]):
        params = host_random_params(model_f, sample)
        qparams = quantize_params(params)
        del params
    qparams = jax.device_put(
        qparams, jax.devices()[0])

    prompt = jax.random.randint(jax.random.PRNGKey(0), (batch, prompt_len),
                                0, cfg.vocab_size)
    run = jax.jit(lambda p, t: generate(cfg, p, t, new_tokens))
    np.asarray(run(qparams, prompt))  # compile + warmup (value transfer)
    best = 0.0
    for i in range(3):
        p = jax.random.randint(jax.random.PRNGKey(100 + i),
                               (batch, prompt_len), 0, cfg.vocab_size)
        np.asarray(p)
        t0 = time.perf_counter()
        np.asarray(run(qparams, p))
        best = max(best, batch * new_tokens / (time.perf_counter() - t0))

    from kubeflow_tpu.models.quant import quantized_bytes

    w_bytes = quantized_bytes(qparams)  # streamed: embed lookup excluded
    resident_bytes = quantized_bytes(qparams, exclude=())  # HBM residency
    kv_bytes = (2 * batch * cfg.max_seq_len * cfg.num_kv_heads
                * cfg.head_dim * 2 * cfg.num_layers)
    roofline = ACCELERATORS["v5e"].hbm_gbps * 1e9 / (w_bytes + kv_bytes) * batch
    print(json.dumps({
        "metric": "decode_tok_s_v5e_llama7b_int8",
        "value": round(best, 1),
        "unit": "tokens/s",
        "vs_baseline": round(best / roofline, 4),
        "detail": {
            "model": "llama2-7b-arch", "batch": batch,
            "prompt_len": prompt_len, "new_tokens": new_tokens,
            "weight_gb": round(resident_bytes / 2**30, 2),
            "streamed_weight_gb": round(w_bytes / 2**30, 2),
            "hbm_roofline_tok_s": round(roofline, 1),
        },
    }))


if __name__ == "__main__":
    main()
