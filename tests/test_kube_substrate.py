"""Tests for the in-memory control-plane substrate (our envtest analog)."""

import pytest

from kubeflow_tpu.kube import (
    AdmissionDenied,
    AdmissionHook,
    ApiServer,
    ConflictError,
    EventRecorder,
    FakeCluster,
    KubeObject,
    Manager,
    NotFoundError,
    ObjectMeta,
    Request,
    Result,
    WatchSpec,
    retry_on_conflict,
    set_controller_reference,
)
from kubeflow_tpu.utils.clock import FakeClock


def mk(kind, name, ns="default", labels=None, spec=None, api_version="v1"):
    return KubeObject(
        api_version=api_version,
        kind=kind,
        metadata=ObjectMeta(name=name, namespace=ns, labels=dict(labels or {})),
        body={"spec": spec or {}},
    )


class TestApiServer:
    def test_create_get_list(self):
        api = ApiServer()
        api.create(mk("ConfigMap", "a", labels={"x": "1"}))
        api.create(mk("ConfigMap", "b", ns="other"))
        got = api.get("ConfigMap", "default", "a")
        assert got.metadata.uid and got.metadata.resource_version > 0
        assert len(api.list("ConfigMap")) == 2
        assert len(api.list("ConfigMap", namespace="default")) == 1
        assert len(api.list("ConfigMap", label_selector={"x": "1"})) == 1
        assert api.list("ConfigMap", label_selector={"x": "2"}) == []

    def test_generate_name(self):
        api = ApiServer()
        obj = KubeObject("v1", "ConfigMap", ObjectMeta(generate_name="nb-", namespace="d"))
        created = api.create(obj)
        assert created.name.startswith("nb-") and len(created.name) > 3

    def test_update_conflict(self):
        api = ApiServer()
        api.create(mk("ConfigMap", "a"))
        c1 = api.get("ConfigMap", "default", "a")
        c2 = api.get("ConfigMap", "default", "a")
        c1.body["data"] = {"k": "1"}
        api.update(c1)
        c2.body["data"] = {"k": "2"}
        with pytest.raises(ConflictError):
            api.update(c2)
        # retry_on_conflict with a fresh read succeeds
        def attempt():
            fresh = api.get("ConfigMap", "default", "a")
            fresh.body["data"] = {"k": "2"}
            api.update(fresh)
        retry_on_conflict(attempt)
        assert api.get("ConfigMap", "default", "a").body["data"] == {"k": "2"}

    def test_status_subresource_isolation(self):
        api = ApiServer()
        api.create(mk("Notebook", "nb", spec={"x": 1}, api_version="kubeflow.org/v1"))
        obj = api.get("Notebook", "default", "nb")
        obj.status = {"readyReplicas": 1}
        api.update_status(obj)
        # a spec update must not clobber status, and vice versa
        obj2 = api.get("Notebook", "default", "nb")
        obj2.spec = {"x": 2}
        api.update(obj2)
        live = api.get("Notebook", "default", "nb")
        assert live.status == {"readyReplicas": 1}
        assert live.spec == {"x": 2}
        assert live.metadata.generation == 2  # spec change bumps generation

    def test_merge_patch_null_deletes(self):
        api = ApiServer()
        nb = mk("Notebook", "nb")
        nb.metadata.annotations["kubeflow-resource-stopped"] = "lock"
        api.create(nb)
        api.merge_patch(
            "Notebook", "default", "nb",
            {"metadata": {"annotations": {"kubeflow-resource-stopped": None}}},
        )
        live = api.get("Notebook", "default", "nb")
        assert "kubeflow-resource-stopped" not in live.metadata.annotations

    def test_finalizers_gate_deletion(self):
        api = ApiServer()
        nb = mk("Notebook", "nb")
        nb.metadata.finalizers = ["odh.opendatahub.io/cleanup"]
        api.create(nb)
        api.delete("Notebook", "default", "nb")
        live = api.get("Notebook", "default", "nb")  # still present
        assert live.metadata.deletion_timestamp is not None
        live.metadata.finalizers = []
        api.update(live)
        with pytest.raises(NotFoundError):
            api.get("Notebook", "default", "nb")

    def test_update_without_resource_version_is_unconditional(self):
        """Real-apiserver semantics (verified by the golden fixtures): an
        empty resourceVersion on update means 'no precondition' — the write
        replaces unconditionally instead of being rejected."""
        api = ApiServer()
        created = api.create(mk("ConfigMap", "a"))
        fresh = mk("ConfigMap", "a")  # no resourceVersion
        fresh.metadata.labels["unconditional"] = "yes"
        updated = api.update(fresh)
        assert updated.metadata.labels["unconditional"] == "yes"
        assert updated.metadata.resource_version != \
            created.metadata.resource_version

    def test_gc_waits_for_last_owner(self):
        api = ApiServer()
        o1 = api.create(mk("Notebook", "nb1"))
        o2 = api.create(mk("Notebook", "nb2"))
        shared = mk("ReferenceGrant", "shared")
        shared.metadata.owner_references = [
            o1.owner_reference(controller=False),
            o2.owner_reference(controller=False),
        ]
        api.create(shared)
        api.delete("Notebook", "default", "nb1")
        live = api.get("ReferenceGrant", "default", "shared")  # survives
        assert len(live.metadata.owner_references) == 1
        api.delete("Notebook", "default", "nb2")
        with pytest.raises(NotFoundError):
            api.get("ReferenceGrant", "default", "shared")

    def test_owner_ref_cascade(self):
        api = ApiServer()
        owner = api.create(mk("Notebook", "nb"))
        child = mk("StatefulSet", "nb", api_version="apps/v1")
        set_controller_reference(owner, child)
        api.create(child)
        api.delete("Notebook", "default", "nb")
        with pytest.raises(NotFoundError):
            api.get("StatefulSet", "default", "nb")

    def test_create_with_dead_owner_is_collected(self):
        """Regression: a reconciler racing a cascade delete can create a
        dependent AFTER the owner finalized (the e2e multislice leak).
        Real GC reaps dependents with dangling owner refs; the store must
        do the same at create."""
        api = ApiServer()
        owner = api.create(mk("Notebook", "nb"))
        api.delete("Notebook", "default", "nb")
        child = mk("StatefulSet", "nb-slice-1", api_version="apps/v1")
        child.metadata.owner_references = [owner.owner_reference()]
        api.create(child)  # create succeeds (201), as on a real apiserver
        with pytest.raises(NotFoundError):
            api.get("StatefulSet", "default", "nb-slice-1")

    def test_create_with_terminating_owner_is_collected(self):
        """An owner mid-termination (finalizers pending) must also fence new
        dependents — the cascade at finalize would otherwise race them."""
        api = ApiServer()
        nb = mk("Notebook", "nb")
        nb.metadata.finalizers = ["odh.opendatahub.io/cleanup"]
        owner = api.create(nb)
        api.delete("Notebook", "default", "nb")  # terminating, not gone
        child = mk("StatefulSet", "nb-slice-1", api_version="apps/v1")
        child.metadata.owner_references = [owner.owner_reference()]
        api.create(child)
        with pytest.raises(NotFoundError):
            api.get("StatefulSet", "default", "nb-slice-1")

    def test_create_with_one_dead_one_live_owner_strips_ref(self):
        api = ApiServer()
        dead = api.create(mk("Notebook", "dead"))
        api.delete("Notebook", "default", "dead")
        live_owner = api.create(mk("Notebook", "alive"))
        child = mk("ReferenceGrant", "shared")
        child.metadata.owner_references = [
            dead.owner_reference(controller=False),
            live_owner.owner_reference(controller=False),
        ]
        api.create(child)
        got = api.get("ReferenceGrant", "default", "shared")
        assert [r.name for r in got.metadata.owner_references] == ["alive"]

    def test_admission_mutating_and_validating(self):
        api = ApiServer()

        def mutate(op, old, obj):
            if op == "CREATE":
                obj.metadata.annotations["injected"] = "yes"
            return obj

        def validate(op, old, obj):
            if obj.metadata.labels.get("forbidden") == "true":
                raise AdmissionDenied("forbidden label")

        api.register_admission(AdmissionHook(kinds=("Notebook",), handler=mutate))
        api.register_admission(
            AdmissionHook(kinds=("Notebook",), handler=validate, mutating=False)
        )
        created = api.create(mk("Notebook", "nb"))
        assert created.metadata.annotations["injected"] == "yes"
        with pytest.raises(AdmissionDenied):
            api.create(mk("Notebook", "bad", labels={"forbidden": "true"}))


class _CountingReconciler:
    def __init__(self, api):
        self.api = api
        self.seen = []

    def reconcile(self, req):
        self.seen.append(req)
        return Result()


class TestManager:
    def test_for_owns_watch_routing(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        rec = _CountingReconciler(api)
        mgr.register(
            "nb",
            rec,
            for_kind="Notebook",
            owns=["StatefulSet"],
            watches=[
                WatchSpec(
                    kind="Pod",
                    mapper=lambda pod: (
                        [Request(pod.namespace, pod.labels["notebook-name"])]
                        if "notebook-name" in pod.labels
                        else []
                    ),
                )
            ],
        )
        owner = api.create(mk("Notebook", "nb1"))
        sts = mk("StatefulSet", "nb1", api_version="apps/v1")
        set_controller_reference(owner, sts)
        api.create(sts)
        api.create(mk("Pod", "nb1-0", labels={"notebook-name": "nb1"}))
        api.create(mk("Pod", "random"))  # no label -> no request
        mgr.run_until_idle()
        # workqueue dedupe: three events for the same key collapse to one run
        assert Request("default", "nb1") in rec.seen
        assert all(r.name == "nb1" for r in rec.seen)

    def test_requeue_after_with_fake_clock(self):
        api = ApiServer()
        clock = FakeClock()
        mgr = Manager(api, clock=clock)

        class R:
            def __init__(self):
                self.calls = 0

            def reconcile(self, req):
                self.calls += 1
                return Result(requeue_after=60.0) if self.calls == 1 else Result()

        rec = R()
        mgr.register("nb", rec, for_kind="Notebook")
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        assert rec.calls == 1
        assert len(mgr.pending_delayed()) == 1
        mgr.advance(59.0)
        assert rec.calls == 1
        mgr.advance(2.0)
        assert rec.calls == 2

    def test_error_retry_then_drop(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())

        class Failing:
            def __init__(self):
                self.calls = 0

            def reconcile(self, req):
                self.calls += 1
                raise RuntimeError("boom")

        rec = Failing()
        mgr.register("nb", rec, for_kind="Notebook", max_retries=3)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        assert rec.calls == 4  # initial + 3 retries
        assert len(mgr.dropped_errors) == 1


class TestFakeCluster:
    def test_sts_to_running_pods_and_scale(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1")
        sts = mk("StatefulSet", "nb", api_version="apps/v1", spec={
            "replicas": 2,
            "serviceName": "nb-headless",
            "template": {
                "metadata": {"labels": {"notebook-name": "nb"}},
                "spec": {"containers": [{"name": "main", "image": "img"}]},
            },
        })
        api.create(sts)
        pods = api.list("Pod", namespace="default")
        assert [p.name for p in pods] == ["nb-0", "nb-1"]
        p0 = pods[0]
        assert p0.body["status"]["phase"] == "Running"
        assert p0.spec["hostname"] == "nb-0"
        assert p0.spec["subdomain"] == "nb-headless"
        assert p0.labels["apps.kubernetes.io/pod-index"] == "0"
        live = api.get("StatefulSet", "default", "nb")
        assert live.status["readyReplicas"] == 2
        # scale to zero (cull): pods removed
        live.spec["replicas"] = 0
        api.update(live)
        assert api.list("Pod", namespace="default") == []

    def test_tpu_scheduling_respects_capacity_and_selector(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", num_hosts=1, chips_per_host=4)
        sts = mk("StatefulSet", "tpu-nb", api_version="apps/v1", spec={
            "replicas": 1,
            "template": {"spec": {
                "nodeSelector": {
                    "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                    "cloud.google.com/gke-tpu-topology": "4x4",
                },
                "containers": [{
                    "name": "main", "image": "img",
                    "resources": {"requests": {"google.com/tpu": "4"},
                                  "limits": {"google.com/tpu": "4"}},
                }],
            }},
        })
        api.create(sts)
        pod = api.get("Pod", "default", "tpu-nb-0")
        assert pod.body["status"]["phase"] == "Running"
        assert pod.spec["nodeName"].startswith("tpu-node-")
        # a second slice cannot fit: chips exhausted
        sts2 = mk("StatefulSet", "tpu-nb2", api_version="apps/v1",
                  spec={**sts.spec, "replicas": 1})
        api.create(sts2)
        pod2 = api.get("Pod", "default", "tpu-nb2-0")
        assert pod2.body["status"]["phase"] == "Pending"

    def test_pending_pod_rescheduled_when_node_arrives(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        api.create(mk("StatefulSet", "nb", api_version="apps/v1", spec={
            "replicas": 1,
            "template": {"spec": {"containers": [
                {"name": "main", "resources": {"requests": {"cpu": "1"}}}]}},
        }))
        assert api.get("Pod", "default", "nb-0").body["status"]["phase"] == "Pending"
        cluster.add_node("late-node")  # scheduler retries on node add
        pod = api.get("Pod", "default", "nb-0")
        assert pod.body["status"]["phase"] == "Running"
        assert pod.spec["nodeName"] == "late-node"

    def test_sa_pull_secret_minted(self):
        api = ApiServer()
        FakeCluster(api)
        api.create(mk("ServiceAccount", "nb-sa"))
        secret = api.get("Secret", "default", "nb-sa-dockercfg")
        assert secret.body["type"] == "kubernetes.io/dockercfg"
        sa = api.get("ServiceAccount", "default", "nb-sa")
        assert {"name": "nb-sa-dockercfg"} in sa.body["imagePullSecrets"]

    def test_pod_failure_injection(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1")
        api.create(mk("StatefulSet", "nb", api_version="apps/v1", spec={
            "replicas": 1,
            "template": {"spec": {"containers": [{"name": "main"}]}},
        }))
        cluster.fail_pod("default", "nb-0")
        pod = api.get("Pod", "default", "nb-0")
        assert pod.body["status"]["phase"] == "Failed"
        sts = api.get("StatefulSet", "default", "nb")
        assert sts.status["readyReplicas"] == 0


class TestEventRecorder:
    def test_event_creation_and_aggregation(self):
        api = ApiServer()
        rec = EventRecorder(api, "notebook-controller")
        nb = api.create(mk("Notebook", "nb"))
        rec.event(nb, "Normal", "Created", "created sts")
        rec.event(nb, "Normal", "Created", "created sts")
        events = api.list("Event", namespace="default")
        assert len(events) == 1
        assert events[0].body["count"] == 2
        assert events[0].body["involvedObject"]["name"] == "nb"
