#!/usr/bin/env bash
# Install kind + kubectl for the integration workflow (reference analog:
# components/testing/gh-actions/install_kind.sh).
set -euo pipefail
KIND_VERSION="${KIND_VERSION:-v0.23.0}"
KUBECTL_VERSION="${KUBECTL_VERSION:-v1.30.0}"
BIN="${BIN:-/usr/local/bin}"

curl -fsSLo "${BIN}/kind" \
  "https://kind.sigs.k8s.io/dl/${KIND_VERSION}/kind-linux-amd64"
chmod +x "${BIN}/kind"
curl -fsSLo "${BIN}/kubectl" \
  "https://dl.k8s.io/release/${KUBECTL_VERSION}/bin/linux/amd64/kubectl"
chmod +x "${BIN}/kubectl"
kind version
kubectl version --client
