"""Fleet SLO engine: objectives, multi-window burn rates, and alerts.

PR 8 gave the control plane fleet-scale telemetry (latency histograms,
error counters) but no *verdicts*: nothing said whether the fleet is
meeting its objectives, and nobody watched the streams between loadtest
runs.  NotebookOS (arXiv:2503.20591) argues interactive notebook
platforms live or die on control-plane reaction latency at fleet scale —
which needs a standing signal, not a post-hoc benchmark.  This module is
that signal, in the SRE error-budget formulation:

  - an **Objective** declares a target over an existing metric stream
    (p99 latency under a threshold, reconcile error rate under a cap,
    warm-pool hit rate over a floor).  Objectives come from config
    (`SLO_*` knobs, utils/config.py `default_objectives`), not code.
  - the engine snapshots the cumulative good/bad counts at each
    `evaluate()` (every /metrics scrape calls it) and computes **burn
    rates** over sliding windows (default 5m/1h) off the injected Clock:
    burn = (bad fraction in window) / (allowed bad fraction).  burn > 1
    means the error budget is being spent faster than it accrues.
  - exported families: `notebook_slo_burn_rate{objective,window}`,
    `notebook_slo_error_budget_remaining_ratio{objective}` (long
    window), and `notebook_slo_alert_firing{objective}`.
  - **alerts** follow the multi-window multi-burn pattern: fire when
    EVERY window burns above `burn_threshold` (the short window makes it
    react, the long window keeps blips from paging), resolve when the
    short window recovers.  One active alert per objective (dedup across
    scrapes); history is bounded; each alert latches an exemplar
    trace_id from the attempt stream the Manager feeds
    (`observe_attempt`), so an alert pivots straight into the flight
    recorder (`/debug/traces/<trace_id>`).

Everything reads the injected clock and existing Registry objects — the
engine adds no locks to the reconcile path and costs O(objectives ×
windows) per evaluation.  Served at loopback `/debug/alerts` (main.py).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from .metrics import Histogram, Registry

# objective kinds (bounded set: the `objective` metric label enumerates
# the configured objective NAMES, the kinds just drive the math)
KIND_LATENCY = "latency"      # histogram: p(target_ratio) <= threshold_s
KIND_RATIO = "ratio"          # labeled counter: bad subset under budget


@dataclass(frozen=True)
class Objective:
    """One declared objective over an existing metric family.

    `target_ratio` is the good fraction the SLO promises (0.99 = p99 for
    latency objectives; 1 - max_error_rate for ratio objectives); the
    error budget is `1 - target_ratio` of events per window.

    latency kind: `metric` names a Histogram; an observation is good
    when it lands at or under `threshold_s` (snapped to the nearest
    bucket upper bound >= threshold, the finest the exposition can
    answer; a threshold above every bound counts everything good).

    ratio kind: `metric` names a labeled Counter; `label` selects the
    label dimension, `bad_values` the label values that spend budget,
    and `total_values` restricts the denominator (None = every series,
    e.g. error-rate counts all results; a hit-rate objective counts only
    hit+miss so bypasses are neutral)."""

    name: str
    kind: str
    metric: str
    description: str = ""
    target_ratio: float = 0.99
    threshold_s: float = 0.0                      # latency kind
    label: str = ""                               # ratio kind
    bad_values: tuple[str, ...] = ()              # ratio kind
    total_values: Optional[tuple[str, ...]] = None  # ratio kind

    @property
    def budget_fraction(self) -> float:
        return max(1.0 - self.target_ratio, 1e-9)


@dataclass
class Alert:
    """One fire->resolve lifecycle of an objective's burn alert."""

    objective: str
    fired_at: float
    state: str = "firing"         # firing | resolved
    resolved_at: float = 0.0
    burn_rates: dict = field(default_factory=dict)  # window label -> burn
    trace_id: str = ""            # exemplar: a budget-spending attempt
    seq: int = 0                  # monotonic per engine (dedup audit)

    def to_dict(self) -> dict:
        return {
            "objective": self.objective,
            "state": self.state,
            "fired_at": self.fired_at,
            "resolved_at": self.resolved_at,
            "burn_rates": dict(self.burn_rates),
            "trace_id": self.trace_id,
            "seq": self.seq,
        }


def window_label(seconds: float) -> str:
    """Human window label for the metric ("5m", "1h"), stable for
    dashboards; falls back to seconds for odd sizes."""
    s = int(seconds)
    if s >= 3600 and s % 3600 == 0:
        return f"{s // 3600}h"
    if s >= 60 and s % 60 == 0:
        return f"{s // 60}m"
    return f"{seconds:g}s"


def register_slo_metrics(registry: Registry) -> tuple:
    """The SLO metric families (registered by NotebookMetrics so the
    inventory is stable whether or not an engine is attached; the engine
    re-registers identically and gets the same objects back)."""
    burn = registry.gauge(
        "notebook_slo_burn_rate",
        "Error-budget burn rate per objective and sliding window "
        "(1.0 = spending exactly the budget)",
        labels=("objective", "window"))
    remaining = registry.gauge(
        "notebook_slo_error_budget_remaining_ratio",
        "Fraction of the long-window error budget left per objective "
        "(negative = overspent)",
        labels=("objective",))
    firing = registry.gauge(
        "notebook_slo_alert_firing",
        "Whether the burn alert of an objective is currently firing",
        labels=("objective",))
    return burn, remaining, firing


def default_objectives(cfg) -> tuple[Objective, ...]:
    """The standing fleet objectives, from CoreConfig's SLO_* knobs; a
    knob <= 0 disables its objective.  The warm-pool objective only
    exists when the slice scheduler is on (no pool, no hit rate)."""
    out = []
    if cfg.slo_time_to_ready_p99_s > 0:
        out.append(Objective(
            name="time_to_ready", kind=KIND_LATENCY,
            metric="notebook_to_ready_seconds",
            threshold_s=cfg.slo_time_to_ready_p99_s,
            description="p99 notebook creation -> all workers Ready"))
    if cfg.slo_event_to_reconcile_p99_s > 0:
        out.append(Objective(
            name="event_to_reconcile", kind=KIND_LATENCY,
            metric="notebook_event_to_reconcile_seconds",
            threshold_s=cfg.slo_event_to_reconcile_p99_s,
            description="p99 watch event -> reconcile start (control-"
                        "plane reaction latency)"))
    if cfg.slo_reconcile_error_rate > 0:
        out.append(Objective(
            name="reconcile_errors", kind=KIND_RATIO,
            metric="controller_runtime_reconcile_total",
            target_ratio=1.0 - cfg.slo_reconcile_error_rate,
            label="result", bad_values=("error",),
            description="reconcile attempts ending in error"))
    if cfg.slo_recovery_p99_s > 0:
        out.append(Objective(
            name="recovery_duration", kind=KIND_LATENCY,
            metric="notebook_disruption_recovery_seconds",
            threshold_s=cfg.slo_recovery_p99_s,
            description="p99 disruption detection -> slice Healthy"))
    # replicated-kernel objective (core/selfheal.py promote verb): the
    # tier's promise is sub-second failover, so the default ceiling is
    # 1s — an election that has to wait out follower catch-up or a
    # contended promotion record burns this budget
    if cfg.slo_promotion_p99_s > 0:
        out.append(Objective(
            name="promotion_duration", kind=KIND_LATENCY,
            metric="notebook_promotion_duration_seconds",
            threshold_s=cfg.slo_promotion_p99_s,
            description="p99 primary disruption -> follower promoted "
                        "(replicated notebooks)"))
    # time-to-placement objective (core/scheduler.py tenancy admission):
    # notebook_queue_wait_seconds observes EVERY placement (0 for gangs
    # that never queued), so its p99 under the ceiling is exactly "a
    # gang's wait behind quota/fair share/preemption stays bounded" —
    # the starvation alarm for the priority/queue machinery
    if getattr(cfg, "slo_placement_p99_s", 0.0) > 0:
        out.append(Objective(
            name="time_to_placement", kind=KIND_LATENCY,
            metric="notebook_queue_wait_seconds",
            threshold_s=cfg.slo_placement_p99_s,
            description="p99 quota/fair-share queue wait before the "
                        "placement intent lands"))
    if cfg.enable_slice_scheduler and cfg.slo_warmpool_hit_rate > 0:
        out.append(Objective(
            name="warmpool_hit_rate", kind=KIND_RATIO,
            metric="notebook_warmpool_hits_total",
            target_ratio=cfg.slo_warmpool_hit_rate,
            label="result", bad_values=("miss",),
            total_values=("hit", "miss"),
            description="warm-pool claims served from a pre-provisioned "
                        "slice"))
    # sharded-control-plane objective (kube/shard.py handoff histogram):
    # knob-disabled by default — it only means something when SHARD_COUNT
    # > 1 runs an actual fleet.  A handoff that stalls (dead member not
    # yet evicted, drain ack waiting out in-flight keys) lands in a fat
    # bucket and burns this budget, firing the multi-window alert.
    if cfg.slo_shard_handoff_p99_s > 0:
        out.append(Objective(
            name="shard_handoff", kind=KIND_LATENCY,
            metric="notebook_shard_handoff_duration_seconds",
            threshold_s=cfg.slo_shard_handoff_p99_s,
            description="p99 shard-map handoff duration (membership "
                        "commit -> completing ack)"))
    # data-plane objectives (core/telemetry.py verdict counters): both
    # knob-disabled by default — they only mean something on fleets whose
    # workers actually publish telemetry annotations
    if cfg.slo_fleet_mfu > 0:
        out.append(Objective(
            name="fleet_mfu", kind=KIND_RATIO,
            metric="notebook_dataplane_mfu_checks_total",
            target_ratio=cfg.slo_fleet_mfu,
            label="result", bad_values=("low",),
            description="per-notebook MFU evaluations at or above "
                        "DATAPLANE_MFU_TARGET"))
    if cfg.slo_straggler_rate > 0:
        out.append(Objective(
            name="straggler_rate", kind=KIND_RATIO,
            metric="notebook_dataplane_straggler_checks_total",
            target_ratio=1.0 - cfg.slo_straggler_rate,
            label="result", bad_values=("straggler",),
            description="per-notebook straggler evaluations finding the "
                        "slice stepping together"))
    # tenant-fairness objective (utils/metering.py verdict counter): each
    # metering evaluation votes ok/noisy; a noisy-neighbor episode burns
    # this budget and fires an alert whose exemplar is the latched trace
    # of the flooding tenant (TenantMeteringLedger.evaluate latches it
    # via latch_exemplar).
    if getattr(cfg, "slo_tenant_fairness", 0.0) > 0:
        out.append(Objective(
            name="tenant_fairness", kind=KIND_RATIO,
            metric="notebook_tenant_fairness_checks_total",
            target_ratio=1.0 - cfg.slo_tenant_fairness,
            label="result", bad_values=("noisy",),
            description="metering rounds finding no tenant over its fair "
                        "control-plane share while others degrade"))
    return tuple(out)


class SLOEngine:
    """Windowed burn-rate computation + alert lifecycle over existing
    metric registries; see module docstring.

    `registries` are searched in order for each objective's metric (the
    NotebookMetrics registry and the Manager's reconcile registry are
    disjoint).  Snapshots accumulate only on `evaluate()` — wire it to
    the scrape path (NotebookMetrics.scrape does) and window resolution
    follows the scrape interval, which is exactly the resolution a
    Prometheus-side burn rule would have."""

    def __init__(self, objectives, registries, clock,
                 windows: tuple[float, ...] = (300.0, 3600.0),
                 burn_threshold: float = 2.0,
                 recorder=None, max_alerts: int = 256) -> None:
        self.objectives: tuple[Objective, ...] = tuple(objectives)
        self.registries = list(registries)
        self.clock = clock
        self.windows = tuple(sorted(windows))
        self.burn_threshold = burn_threshold
        self.recorder = recorder
        self._lock = threading.Lock()
        # (t, {objective: (good, bad)}) snapshots, pruned past the long
        # window (one sample older than the boundary is kept so the
        # window-start interpolation always has an anchor)
        self._samples: deque[tuple[float, dict]] = deque()
        self._active: dict[str, Alert] = {}
        self._history: deque[Alert] = deque(maxlen=max_alerts)
        self._alert_seq = 0
        self._last_eval: dict[str, dict] = {}
        self.evaluations = 0
        # exemplar latches fed by Manager via observe_attempt(): the most
        # recent budget-spending attempt per flavor, so a firing alert
        # carries a trace id that resolves in the flight recorder
        self._last_error_trace = ""
        self._slowest_trace = ""
        self._slowest_duration = -1.0
        # objective-name -> trace id latched by an external detector
        # (e.g. the tenant metering ledger when it flags a noisy
        # neighbor); checked before the generic flavor latches
        self._latched_exemplars: dict[str, str] = {}
        reg = self.registries[0] if self.registries else Registry()
        self.burn_gauge, self.remaining_gauge, self.firing_gauge = \
            register_slo_metrics(reg)
        # baseline snapshot: burn starts measuring from engine birth, not
        # from the absolute counter values of a long-lived process
        self.evaluate()

    # -- attempt feed (Manager, on flight-recorder record) --------------------
    def observe_attempt(self, rec) -> None:
        """Latch exemplar trace ids off the completed-attempt stream
        (kube/controller.py calls this with each AttemptRecord)."""
        with self._lock:
            if rec.trace_id:
                if rec.result == "error" or rec.error:
                    self._last_error_trace = rec.trace_id
                if rec.duration_s >= self._slowest_duration:
                    self._slowest_duration = rec.duration_s
                    self._slowest_trace = rec.trace_id

    def latch_exemplar(self, objective: str, trace) -> None:
        """Pin the exemplar trace a firing alert of `objective` should
        carry.  Detectors that know the concrete culprit (the metering
        ledger's noisy tenant) feed this; `trace` is a trace id string or
        a dict with a "trace_id" key."""
        trace_id = (trace.get("trace_id", "") if isinstance(trace, dict)
                    else str(trace or ""))
        if not trace_id:
            return
        with self._lock:
            self._latched_exemplars[objective] = trace_id

    # -- metric resolution ----------------------------------------------------
    def _metric(self, name: str):
        for reg in self.registries:
            m = reg.get(name)
            if m is not None:
                return m
        return None

    def _totals(self, obj: Objective) -> tuple[float, float]:
        """Cumulative (good, bad) event counts for one objective, summed
        over every label set of its metric family."""
        m = self._metric(obj.metric)
        if m is None:
            return 0.0, 0.0
        if obj.kind == KIND_LATENCY and isinstance(m, Histogram):
            # snap the threshold to the nearest bucket upper bound >= it;
            # none (threshold above the last bound) means every finite
            # observation counts good
            snap = next((b for b in m.buckets if b >= obj.threshold_s),
                        None)
            good = total = 0.0
            for key in m.collect():
                counts = m.bucket_counts(*key)
                inf = counts[float("inf")]
                total += inf
                good += counts[snap] if snap is not None else inf
            return good, total - good
        if obj.kind == KIND_RATIO:
            try:
                idx = m.label_names.index(obj.label)
            except ValueError:
                return 0.0, 0.0
            good = bad = 0.0
            for key, v in m.collect().items():
                value = key[idx]
                if obj.total_values is not None and \
                        value not in obj.total_values:
                    continue
                if value in obj.bad_values:
                    bad += v
                else:
                    good += v
            return good, bad
        return 0.0, 0.0

    def _window_start(self, name: str, since: float) -> tuple[float, float]:
        """The (good, bad) counts at the newest snapshot taken at or
        before `since`; the engine's birth snapshot anchors windows older
        than its history."""
        anchor = (0.0, 0.0)
        for t, totals in self._samples:
            if t > since:
                break
            anchor = totals.get(name, anchor)
        return anchor

    def _exemplar_for(self, obj: Objective) -> str:
        latched = self._latched_exemplars.get(obj.name, "")
        if latched:
            return latched
        if obj.kind == KIND_RATIO and obj.bad_values == ("error",):
            return self._last_error_trace
        if obj.kind == KIND_LATENCY:
            # prefer a stored histogram exemplar from a bucket above the
            # threshold (the concrete slow observation), else the slowest
            # attempt the Manager fed us
            m = self._metric(obj.metric)
            if isinstance(m, Histogram):
                for key in m.collect():
                    for bound, (labels, _v) in sorted(
                            m.exemplar(*key).items(), reverse=True):
                        if bound > obj.threshold_s and labels.get("trace_id"):
                            return labels["trace_id"]
            return self._slowest_trace
        return ""

    # -- evaluation -----------------------------------------------------------
    def evaluate(self) -> dict[str, dict]:
        """Take a snapshot, recompute burn rates / budgets / alert state,
        update the exported gauges, and return the per-objective stats.
        Deterministic under FakeClock; call it from the scrape path or
        directly in tests."""
        now = self.clock.now()
        totals = {o.name: self._totals(o) for o in self.objectives}
        with self._lock:
            self.evaluations += 1
            if self._samples and self._samples[-1][0] > now:
                # two scrapes raced: keep the sample ring start-sorted
                # (window anchoring walks it in time order)
                now = self._samples[-1][0]
            self._samples.append((now, totals))
            # prune, keeping one anchor at/just-before the long-window edge
            horizon = now - self.windows[-1]
            while len(self._samples) > 1 and self._samples[1][0] <= horizon:
                self._samples.popleft()
            out: dict[str, dict] = {}
            for obj in self.objectives:
                good_now, bad_now = totals[obj.name]
                burns: dict[str, float] = {}
                short_events = 0.0
                for w in self.windows:
                    g0, b0 = self._window_start(obj.name, now - w)
                    dg = max(good_now - g0, 0.0)
                    db = max(bad_now - b0, 0.0)
                    window_total = dg + db
                    frac_bad = db / window_total if window_total > 0 else 0.0
                    burns[window_label(w)] = frac_bad / obj.budget_fraction
                    if w == self.windows[0]:
                        short_events = window_total
                # budget remaining over the long window: 1 - spent/allowed
                g0, b0 = self._window_start(obj.name, now - self.windows[-1])
                long_total = max(good_now - g0, 0.0) + \
                    max(bad_now - b0, 0.0)
                allowed = long_total * obj.budget_fraction
                spent = max(bad_now - b0, 0.0)
                remaining = 1.0 - spent / allowed if allowed > 0 else 1.0
                remaining = max(remaining, -10.0)  # bounded for dashboards
                self._transition_alert(obj, burns, short_events, now)
                stats = {
                    "kind": obj.kind,
                    "metric": obj.metric,
                    "description": obj.description,
                    "target_ratio": obj.target_ratio,
                    "threshold_s": obj.threshold_s or None,
                    "burn_rates": burns,
                    "budget_remaining_ratio": round(remaining, 6),
                    "events_long_window": long_total,
                    "firing": obj.name in self._active,
                }
                out[obj.name] = stats
                self._last_eval[obj.name] = stats
                for label, burn in burns.items():
                    self.burn_gauge.labels(obj.name, label).set(burn)
                self.remaining_gauge.labels(obj.name).set(remaining)
                self.firing_gauge.labels(obj.name).set(
                    1.0 if obj.name in self._active else 0.0)
            # reset the slowest-latch per evaluation so a one-off outlier
            # does not pin the exemplar forever
            self._slowest_duration = -1.0
            return out

    def _transition_alert(self, obj: Objective, burns: dict[str, float],
                          short_events: float, now: float) -> None:
        """Multi-window multi-burn lifecycle (caller holds the lock):
        fire when every window burns above threshold (and the short
        window actually saw events), resolve when the short window
        recovers.  One active alert per objective — continued breach
        across scrapes dedups into the same alert; a breach after a
        resolve fires a fresh one."""
        breach = short_events > 0 and all(
            b >= self.burn_threshold for b in burns.values())
        active = self._active.get(obj.name)
        short_label = window_label(self.windows[0])
        if breach and active is None:
            self._alert_seq += 1
            alert = Alert(objective=obj.name, fired_at=now,
                          burn_rates=dict(burns),
                          trace_id=self._exemplar_for(obj),
                          seq=self._alert_seq)
            self._active[obj.name] = alert
            self._history.append(alert)
        elif active is not None:
            if burns.get(short_label, 0.0) < self.burn_threshold:
                active.state = "resolved"
                active.resolved_at = now
                del self._active[obj.name]
            else:
                # still burning: refresh the observed rates (same alert)
                active.burn_rates = dict(burns)

    # -- read side (/debug/alerts, loadtest, tests) ---------------------------
    def firing(self) -> list[Alert]:
        with self._lock:
            return list(self._active.values())

    def alert_history(self) -> list[Alert]:
        with self._lock:
            return list(self._history)

    def snapshot(self) -> dict:
        """The /debug/alerts body: objective stats from the last
        evaluation, currently-firing alerts, and the bounded fire/resolve
        history (each alert carrying its exemplar trace_id — resolve it
        at /debug/traces/<trace_id>)."""
        with self._lock:
            return {
                "now": self.clock.now(),
                "burn_threshold": self.burn_threshold,
                "windows": [window_label(w) for w in self.windows],
                "evaluations": self.evaluations,
                "objectives": {k: dict(v)
                               for k, v in self._last_eval.items()},
                "firing": [a.to_dict() for a in self._active.values()],
                "history": [a.to_dict() for a in self._history],
            }

    def verdicts(self) -> dict[str, dict]:
        """End-of-run verdict per objective (loadtest --out records
        these): met = the long window closed within budget."""
        stats = self.evaluate()
        long_label = window_label(self.windows[-1])
        return {
            name: {
                "met": s["budget_remaining_ratio"] >= 0.0,
                "burn_rate": s["burn_rates"].get(long_label, 0.0),
                "budget_remaining_ratio": s["budget_remaining_ratio"],
                "events": s["events_long_window"],
            }
            for name, s in stats.items()
        }


__all__ = ["Alert", "Objective", "SLOEngine", "default_objectives",
           "register_slo_metrics", "window_label",
           "KIND_LATENCY", "KIND_RATIO"]
