"""Checkpoint/restore hooks with cull-signal + session-store integration.

The reference has no in-process checkpointing — all state is CR annotations
(SURVEY.md §5 "Checkpoint/resume").  A TPU notebook does real training, so
the runtime pairs Orbax with the culling controller's checkpoint-before-cull
protocol (core/constants.py ANNOTATION_CHECKPOINT_REQUESTED/_COMPLETE):

  controller sets  checkpoint-requested  ->  (downward-API file appears)
  runtime saves + acks via the signal file ->  controller proceeds to cull

The signal transport is a file because annotations are projected into pods
via the downward API; tests drive the same path with a tmp file.

Two extensions ride on top:

- **Torn-write safety.**  `CheckpointManager` grows a pure-python `local`
  backend (the default when orbax is absent) whose `save` writes a temp
  file, fsyncs, then atomically renames — and whose `restore` skips and
  garbage-collects partial/corrupt writes, so a worker killed mid-save can
  never resurrect a half-written step.

- **The session-state tier** (core/sessionstate.py): `CheckpointSidecar`
  implements the pod side of the checkpoint-sidecar contract the
  controller renders into the StatefulSet template — periodic snapshots
  every CHECKPOINT_INTERVAL_S into CHECKPOINT_STORE_URI, a forced
  snapshot + acknowledge when the cull signal fires, and
  `restore_instructions`/`restore_payload` consuming the
  CHECKPOINT_RESTORE_URI/_GENERATION env the migrate verb stamps into
  recreated pods.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Mapping, Optional

DEFAULT_SIGNAL_DIR = "/etc/podinfo"
REQUEST_FILE = "checkpoint-requested"
ACK_FILE = "checkpoint-complete"

# the sidecar contract env (mirrors core.constants ENV_CHECKPOINT_*)
ENV_STORE_URI = "CHECKPOINT_STORE_URI"
ENV_INTERVAL_S = "CHECKPOINT_INTERVAL_S"
ENV_RESTORE_URI = "CHECKPOINT_RESTORE_URI"
ENV_RESTORE_GENERATION = "CHECKPOINT_RESTORE_GENERATION"

_STEP_PREFIX = "step_"
_STEP_SUFFIX = ".ckpt"
_TMP_PREFIX = ".tmp-"


def _to_host(tree: Any) -> Any:
    """Device arrays -> host numpy before pickling (a local checkpoint must
    not capture device buffers)."""
    try:
        import jax
        import numpy as np
    except ImportError:
        return tree
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _like(state_like: Any, stored: Any) -> Any:
    """Re-materialize restored leaves in the shape/type of `state_like`
    (the orbax StandardRestore analog)."""
    try:
        import jax
        import jax.numpy as jnp
    except ImportError:
        return stored
    if state_like is None:
        return jax.tree.map(jnp.asarray, stored)
    return jax.tree.map(lambda _, v: jnp.asarray(v), state_like, stored)


class CheckpointManager:
    """Sharded async-capable save/restore keyed by step.

    backend="orbax" (the default when orbax is importable) delegates to an
    Orbax CheckpointManager — multi-host safe, every process must call
    save/restore collectively.  backend="local" is the dependency-free
    single-host path with torn-write hardening: save is temp-write ->
    fsync -> atomic rename, restore walks steps newest-first, skipping and
    GC-ing anything partial or unreadable.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 backend: str = "auto"):
        self.directory = Path(directory)
        self.max_to_keep = max_to_keep
        if backend == "auto":
            try:
                import orbax.checkpoint  # noqa: F401

                backend = "orbax"
            except ImportError:
                backend = "local"
        self.backend = backend
        if backend == "orbax":
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self.manager = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True
                ),
            )
        else:
            self.manager = None
            self.directory.mkdir(parents=True, exist_ok=True)
            self._gc_partials()

    # -- local backend ---------------------------------------------------------
    def _step_path(self, step: int) -> Path:
        return self.directory / f"{_STEP_PREFIX}{step}{_STEP_SUFFIX}"

    def _local_steps(self) -> list[int]:
        steps = []
        for p in self.directory.glob(f"{_STEP_PREFIX}*{_STEP_SUFFIX}"):
            raw = p.name[len(_STEP_PREFIX):-len(_STEP_SUFFIX)]
            if raw.isdigit():
                steps.append(int(raw))
        return sorted(steps)

    def _gc_partials(self) -> None:
        """Temp files under the checkpoint dir are saves that never reached
        their atomic rename (killed mid-save): dead weight, never visible
        as checkpoints — reclaim them."""
        for tmp in self.directory.glob(f"{_TMP_PREFIX}*"):
            try:
                tmp.unlink()
            except OSError:
                pass

    def _local_save(self, step: int, state: Any) -> None:
        payload = pickle.dumps(_to_host(state))
        final = self._step_path(step)
        tmp = self.directory / f"{_TMP_PREFIX}{final.name}-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        # the atomic commit point: a crash before this line leaves only
        # the tmp file (GC'd later), a crash after it a complete step
        os.replace(tmp, final)
        dirfd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        for stale in self._local_steps()[:-self.max_to_keep]:
            try:
                self._step_path(stale).unlink()
            except OSError:
                pass

    def _local_restore(self, state_like: Any,
                       step: Optional[int]) -> Any:
        self._gc_partials()
        candidates = [step] if step is not None else \
            list(reversed(self._local_steps()))
        for s in candidates:
            path = self._step_path(s)
            try:
                stored = pickle.loads(path.read_bytes())
            except (OSError, pickle.UnpicklingError, EOFError,
                    AttributeError, ValueError):
                # unreadable/corrupt step: GC it and fall back to the
                # next-older checkpoint instead of failing the boot
                try:
                    path.unlink()
                except OSError:
                    pass
                continue
            return _like(state_like, stored)
        return None

    # -- shared surface --------------------------------------------------------
    def save(self, step: int, state: Any, wait: bool = False) -> None:
        if self.backend == "orbax":
            self.manager.save(step, args=self._ocp.args.StandardSave(state))
            if wait:
                self.manager.wait_until_finished()
        else:
            self._local_save(step, state)

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        if self.backend == "orbax":
            step = step if step is not None else self.manager.latest_step()
            if step is None:
                return None
            return self.manager.restore(
                step, args=self._ocp.args.StandardRestore(state_like)
            )
        return self._local_restore(state_like, step)

    def latest_step(self) -> Optional[int]:
        if self.backend == "orbax":
            return self.manager.latest_step()
        steps = self._local_steps()
        return steps[-1] if steps else None

    def close(self) -> None:
        if self.backend == "orbax":
            self.manager.wait_until_finished()
            self.manager.close()


class CullSignalWatcher:
    """Watches for the controller's checkpoint-before-cull request.

    `check()` is cheap enough for a per-step call; `acknowledge()` writes the
    completion marker the culling controller's checkpoint gate polls for
    (core/culling_controller.py)."""

    def __init__(self, signal_dir: str = DEFAULT_SIGNAL_DIR,
                 time_fn: Callable[[], float] = time.time):
        self.signal_dir = Path(signal_dir)
        self.time_fn = time_fn  # same injectable idiom as CheckpointSidecar

    def check(self) -> bool:
        req = self.signal_dir / REQUEST_FILE
        try:
            return req.exists() and req.read_text().strip() not in ("", "false")
        except OSError:
            return False

    def acknowledge(self) -> None:
        self.signal_dir.mkdir(parents=True, exist_ok=True)
        (self.signal_dir / ACK_FILE).write_text(str(self.time_fn()))


def checkpoint_on_cull(
    manager: CheckpointManager,
    watcher: Optional[CullSignalWatcher] = None,
) -> Callable[[int, Any], bool]:
    """Returns a per-step hook: `hook(step, state)` saves synchronously and
    acknowledges when a cull is pending; returns True when it fired so the
    training loop can drain/exit cleanly."""
    watcher = watcher or CullSignalWatcher()
    fired = threading.Event()

    def hook(step: int, state: Any) -> bool:
        if fired.is_set() or not watcher.check():
            return False
        manager.save(step, state, wait=True)
        watcher.acknowledge()
        fired.set()
        return True

    return hook


# -- session-state sidecar (the pod side of the migrate contract) --------------
@dataclass(frozen=True)
class RestoreInstruction:
    """What a recreated pod of a migrated slice must restore: stamped into
    the pod env by the recovery engine (CHECKPOINT_RESTORE_*)."""

    uri: str
    generation: int


def restore_instructions(
        env: Optional[Mapping[str, str]] = None) -> Optional[RestoreInstruction]:
    env = env if env is not None else os.environ
    uri = env.get(ENV_RESTORE_URI, "").strip()
    raw = env.get(ENV_RESTORE_GENERATION, "").strip()
    if not uri or not raw:
        return None
    try:
        return RestoreInstruction(uri=uri, generation=int(raw))
    except ValueError:
        return None


class CheckpointSidecar:
    """Periodic + pre-stop/cull session snapshots into the session-state
    store (core/sessionstate.py), addressed by notebook identity.

    Drive `maybe_snapshot(step, payload_fn)` from the training/serving
    loop: it snapshots when the periodic interval elapsed, and immediately
    (plus acknowledges) when the cull signal file appears.  `payload_fn`
    returns the serialized session bytes only when actually needed."""

    def __init__(self, store, namespace: str, notebook: str, slice_id: int,
                 interval_s: float = 300.0,
                 watcher: Optional[CullSignalWatcher] = None,
                 time_fn: Callable[[], float] = time.time):
        self.store = store
        self.namespace = namespace
        self.notebook = notebook
        self.slice_id = slice_id
        self.interval_s = interval_s
        self.watcher = watcher
        self.time_fn = time_fn
        self._last_snapshot: Optional[float] = None
        self._cull_acked = False

    @classmethod
    def from_env(cls, namespace: str, notebook: str, slice_id: int,
                 env: Optional[Mapping[str, str]] = None,
                 watcher: Optional[CullSignalWatcher] = None,
                 time_fn: Callable[[], float] = time.time
                 ) -> Optional["CheckpointSidecar"]:
        """Build from the rendered sidecar contract; None when the
        controller did not configure a store (contract absent)."""
        env = env if env is not None else os.environ
        uri = env.get(ENV_STORE_URI, "").strip()
        if not uri:
            return None
        try:
            interval = float(env.get(ENV_INTERVAL_S, "") or 300.0)
        except ValueError:
            interval = 300.0
        from ..core.sessionstate import open_store

        return cls(open_store(uri), namespace, notebook, slice_id,
                   interval_s=interval, watcher=watcher, time_fn=time_fn)

    def maybe_snapshot(self, payload_fn: Callable[[], bytes]):
        """Returns the SnapshotInfo written this call, or None."""
        now = self.time_fn()
        if self.watcher is not None and not self._cull_acked \
                and self.watcher.check():
            info = self.store.put(self.namespace, self.notebook,
                                  self.slice_id, payload_fn(),
                                  trigger="cull")
            self.watcher.acknowledge()
            self._cull_acked = True
            self._last_snapshot = now
            return info
        if self._last_snapshot is not None and \
                now - self._last_snapshot < self.interval_s:
            return None
        info = self.store.put(self.namespace, self.notebook, self.slice_id,
                              payload_fn(), trigger="periodic")
        self._last_snapshot = now
        return info

    def snapshot_now(self, payload: bytes, trigger: str = "pre-stop"):
        """The pre-stop hook path: one last flush before the pod dies."""
        self._last_snapshot = self.time_fn()
        return self.store.put(self.namespace, self.notebook, self.slice_id,
                              payload, trigger=trigger)

    def restore_payload(
            self, env: Optional[Mapping[str, str]] = None) -> Optional[bytes]:
        """The boot path of a migrated pod: fetch the stamped generation's
        payload (None -> cold start)."""
        instr = restore_instructions(env)
        if instr is None:
            return None
        return self.store.payload(self.namespace, self.notebook,
                                  self.slice_id, instr.generation)
