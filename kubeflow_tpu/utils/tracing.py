"""Minimal OpenTelemetry-style tracing with OTLP/HTTP export.

The reference traces its mutating webhook with OTel — a lazily-created tracer
(sync.OnceValue, notebook_mutating_webhook.go:74-76), a root span per
admission with notebook attributes (:366-373), child spans, and span events
that the test suite asserts on via an in-memory exporter
(opentelemetry_test.go:26-78).  We keep the same shape: a process-global
provider that defaults to noop, swappable for an InMemorySpanExporter in
tests — tracing as a test observability channel — plus an OtlpHttpExporter
(the OTLP/HTTP JSON protocol, POST {endpoint}/v1/traces) so spans leave the
process in production: set OTEL_EXPORTER_OTLP_ENDPOINT and the manager
wires it at startup (setup_exporter_from_env).

Spans are ALWAYS recorded in-process (they feed the reconcile flight
recorder, utils/flightrecorder.py, which must work in the standalone pod
with no trace backend at all); whether a finished span LEAVES the process
is a separate decision made by the installed exporter.  Production export
is tail-based (TailSampler): the full span tree of an attempt is buffered
until its root finishes, then exported when the attempt errored or was
slow, else kept with a small probability — errors and outliers always
reach the backend while the fast-success firehose stays in-process.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import random
import threading
import time
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Iterator, Optional

logger = logging.getLogger("kubeflow_tpu.tracing")

# injectable time source so span timelines are deterministic under a
# FakeClock (set_clock); None falls back to the wall clock
_clock = None


def set_clock(clock) -> None:
    """Route span/event timestamps through `clock.now()` (a FakeClock in
    tests makes trace timelines deterministic); None restores time.time."""
    global _clock
    _clock = clock


def _now() -> float:
    c = _clock
    return c.now() if c is not None else time.time()


@dataclass
class SpanEvent:
    name: str
    attributes: dict = field(default_factory=dict)
    timestamp: float = 0.0


@dataclass
class Span:
    name: str
    attributes: dict = field(default_factory=dict)
    events: list[SpanEvent] = field(default_factory=list)
    parent: Optional["Span"] = None
    start_time: float = 0.0
    end_time: float = 0.0
    recording: bool = True
    # W3C-style ids (hex): all spans of one trace share trace_id
    trace_id: str = ""
    span_id: str = ""
    # finished child spans, linked by the tracer when each child ends — the
    # span tree the flight recorder pulls per-phase durations from
    children: list["Span"] = field(default_factory=list)

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        if self.recording:
            self.events.append(SpanEvent(name, dict(attributes or {}), _now()))

    def set_attribute(self, key: str, value) -> None:
        if self.recording:
            self.attributes[key] = value


_NOOP_SPAN = Span(name="", recording=False)

# The active-span stack, shared by every Tracer in the process (OTel's
# context propagation): a child span started anywhere inside a reconcile —
# a controller phase, the admission webhook re-entered through an ApiServer
# write, a fault injection — parents onto the live reconcile span.  A
# contextvar is per-thread (and per-async-task), so threaded managers and
# webhook callouts cannot cross-contaminate each other's stacks.
_SPAN_STACK: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "kubeflow_tpu_span_stack", default=())

# Cross-thread mirror of the live span stacks, keyed by thread ident.  A
# contextvar is only readable from its own thread, but the sampling
# profiler (utils/profiler.py) must attribute ANOTHER thread's stack
# frames to the (controller, phase) span that thread is currently inside.
# Updated on every span start/end with plain (GIL-atomic) dict ops — two
# dict assignments per span, no lock on the reconcile path.
_LIVE_STACKS: dict[int, tuple] = {}


def current_span() -> Span:
    """The innermost live span on this thread/context (noop when none) —
    the hook kube.faults uses to stamp injected faults onto whichever
    reconcile attempt the fault actually hit."""
    stack = _SPAN_STACK.get()
    return stack[-1] if stack else _NOOP_SPAN


def live_span_stacks() -> dict[int, tuple]:
    """Snapshot of every thread's live span stack (thread ident ->
    innermost-last Span tuple) — the profiler's attribution source."""
    return dict(_LIVE_STACKS)


class InMemorySpanExporter:
    """Collects finished spans for test assertions
    (opentelemetry_test.go InMemoryExporter analog)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: list[Span] = []

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def events(self) -> list[str]:
        return [e.name for s in self.spans for e in s.events]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class Tracer:
    def __init__(self, name: str) -> None:
        self.name = name

    def current_span(self) -> Span:
        return current_span()

    @contextlib.contextmanager
    def start_span(
        self, name: str, attributes: Optional[dict] = None,
        trace_id: str = "",
    ) -> Iterator[Span]:
        """Open a span as a child of the context's current span.  For a ROOT
        span (no parent on the stack) `trace_id` pins the trace identity —
        the manager passes the same id for every retry of one reconcile
        request so its attempts line up on one trace timeline.

        The span is always recorded (the flight recorder consumes the tree
        even with no exporter installed); it is exported only when an
        exporter is present, resolved at span END so a TailSampler sees the
        finished root and can decide for the whole attempt."""
        stack = _SPAN_STACK.get()
        parent = stack[-1] if stack else None
        span = Span(
            name=name,
            attributes=dict(attributes or {}),
            parent=parent,
            start_time=_now(),
            trace_id=parent.trace_id if parent
            else (trace_id or os.urandom(16).hex()),
            span_id=os.urandom(8).hex(),
        )
        token = _SPAN_STACK.set(stack + (span,))
        tid = threading.get_ident()
        _LIVE_STACKS[tid] = stack + (span,)
        try:
            yield span
        finally:
            _SPAN_STACK.reset(token)
            if stack:
                _LIVE_STACKS[tid] = stack
            else:
                _LIVE_STACKS.pop(tid, None)
            span.end_time = _now()
            if parent is not None:
                parent.children.append(span)
            exporter = _exporter
            if exporter is not None:
                exporter.export(span)


def _otlp_value(v) -> dict:
    """Encode one attribute value as an OTLP AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: dict) -> list[dict]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


def _nanos(t: float) -> str:
    return str(int(t * 1e9))


class OtlpHttpExporter:
    """OTLP/HTTP JSON span exporter: POST {endpoint}/v1/traces.

    The production counterpart of the test InMemorySpanExporter — the
    reference's webhook tracing is real OpenTelemetry with a pluggable
    provider (notebook_mutating_webhook.go:74-76); this speaks the OTLP
    wire format any collector accepts.  Spans are buffered and flushed by a
    background thread (batch span processor shape); export failures are
    logged and dropped — tracing must never take down the control plane."""

    def __init__(self, endpoint: str, service_name: str = "kubeflow-tpu",
                 headers: Optional[dict] = None,
                 flush_interval_s: float = 5.0, max_batch: int = 512,
                 timeout_s: float = 10.0) -> None:
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.headers = dict(headers or {})
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._buffer: list[Span] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    def export(self, span: Span) -> None:
        with self._lock:
            self._buffer.append(span)
            full = len(self._buffer) >= self.max_batch
        if full:
            self.flush()

    def encode(self, spans: list[Span]) -> dict:
        """ExportTraceServiceRequest JSON for a batch of finished spans."""
        return {"resourceSpans": [{
            "resource": {"attributes": _otlp_attrs(
                {"service.name": self.service_name})},
            "scopeSpans": [{
                "scope": {"name": "kubeflow_tpu.utils.tracing"},
                "spans": [{
                    "traceId": s.trace_id,
                    "spanId": s.span_id,
                    **({"parentSpanId": s.parent.span_id}
                       if s.parent is not None else {}),
                    "name": s.name,
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": _nanos(s.start_time),
                    "endTimeUnixNano": _nanos(s.end_time),
                    "attributes": _otlp_attrs(s.attributes),
                    "events": [{
                        "timeUnixNano": _nanos(e.timestamp),
                        "name": e.name,
                        "attributes": _otlp_attrs(e.attributes),
                    } for e in s.events],
                } for s in spans],
            }],
        }]}

    def flush(self) -> None:
        with self._lock:
            batch, self._buffer = self._buffer, []
        if not batch:
            return
        body = json.dumps(self.encode(batch)).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json", **self.headers})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception as err:  # noqa: BLE001 — drop, never crash
            logger.warning("OTLP export of %d spans failed: %s",
                           len(batch), err)

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            self.flush()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.timeout_s)
        self.flush()


class TailSampler:
    """Tail-based sampling: hold an attempt's spans until its ROOT ends,
    then export the whole tree or drop it, deciding on what actually
    happened — the opposite of head sampling, which must guess before the
    outcome exists.

    Policy (checked in order against the finished root span):
      - `error`: the root carries ``error=True`` or
        ``reconcile.result == "error"`` — ALWAYS exported;
      - `slow`: root duration >= ``slow_threshold_s`` — ALWAYS exported;
      - `probabilistic`: otherwise kept with ``sample_rate`` probability
        from a seeded RNG (deterministic for tests), else dropped.

    Child spans buffer per trace id until their root arrives; retries of
    one request share a trace but run sequentially, so at each root
    completion the buffer holds exactly that attempt's children.  The
    buffer is bounded (`max_buffered_traces`, oldest evicted as dropped)
    so a root that never closes cannot grow memory.  The decision is
    stamped on the root as the `sampling.decision` attribute."""

    def __init__(self, exporter, slow_threshold_s: float = 1.0,
                 sample_rate: float = 0.01, seed: int = 0,
                 max_buffered_traces: int = 4096) -> None:
        self.exporter = exporter
        self.slow_threshold_s = slow_threshold_s
        self.sample_rate = sample_rate
        self.max_buffered_traces = max_buffered_traces
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._buffer: "OrderedDict[str, list[Span]]" = OrderedDict()
        self.exported_total = 0
        self.dropped_total = 0
        self.decisions: dict[str, int] = {}

    def _decide(self, root: Span) -> str:
        """Export reason, or '' to drop the attempt's spans."""
        if root.attributes.get("error") or \
                root.attributes.get("reconcile.result") == "error":
            return "error"
        if root.end_time - root.start_time >= self.slow_threshold_s:
            return "slow"
        if self._rng.random() < self.sample_rate:
            return "probabilistic"
        return ""

    def export(self, span: Span) -> None:
        with self._lock:
            if span.parent is not None:
                bucket = self._buffer.setdefault(span.trace_id, [])
                bucket.append(span)
                self._buffer.move_to_end(span.trace_id)
                while len(self._buffer) > self.max_buffered_traces:
                    _, evicted = self._buffer.popitem(last=False)
                    self.dropped_total += len(evicted)
                return
            batch = self._buffer.pop(span.trace_id, [])
            batch.append(span)
            reason = self._decide(span)
            if reason:
                span.attributes["sampling.decision"] = reason
                self.decisions[reason] = self.decisions.get(reason, 0) + 1
                self.exported_total += len(batch)
            else:
                self.dropped_total += len(batch)
                return
        for s in batch:
            self.exporter.export(s)

    def stats(self) -> dict:
        with self._lock:
            return {
                "exported_total": self.exported_total,
                "dropped_total": self.dropped_total,
                "buffered_traces": len(self._buffer),
                "decisions": dict(self.decisions),
                "slow_threshold_s": self.slow_threshold_s,
                "sample_rate": self.sample_rate,
            }

    def flush(self) -> None:
        """Export anything still buffered (roots that never closed — e.g.
        sampler installed mid-trace), then flush the inner exporter."""
        with self._lock:
            leftovers = [s for batch in self._buffer.values() for s in batch]
            self._buffer.clear()
            self.exported_total += len(leftovers)
        for s in leftovers:
            self.exporter.export(s)
        inner_flush = getattr(self.exporter, "flush", None)
        if callable(inner_flush):
            inner_flush()

    def shutdown(self) -> None:
        self.flush()
        inner = getattr(self.exporter, "shutdown", None)
        if callable(inner):
            inner()


_provider_lock = threading.Lock()
_exporter = None  # anything with .export(Span)


def set_exporter(exporter) -> None:
    """Install the process-wide exporter (InMemorySpanExporter in tests,
    OtlpHttpExporter in production); None restores noop."""
    global _exporter
    with _provider_lock:
        _exporter = exporter


def setup_exporter_from_env(env=None):
    """Install an OtlpHttpExporter when OTEL_EXPORTER_OTLP_ENDPOINT is set
    (the standard OTel env contract; OTEL_SERVICE_NAME optional).  Returns
    the installed exporter (caller owns shutdown()) or None.

    Export is tail-sampled by default: errored and slow attempts always
    leave the process, fast successes with TRACE_TAIL_SAMPLE_RATE
    probability (default 0.01).  TRACE_TAIL_SLOW_THRESHOLD_S tunes the
    slow cut (default 1.0s); TRACE_TAIL_SAMPLING=false restores the old
    export-everything behavior."""
    env = env if env is not None else os.environ
    endpoint = env.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
    if not endpoint:
        return None
    exporter = OtlpHttpExporter(
        endpoint, service_name=env.get("OTEL_SERVICE_NAME", "kubeflow-tpu"))
    installed = exporter
    if env.get("TRACE_TAIL_SAMPLING", "true").lower() not in (
            "0", "false", "no", "off"):
        installed = TailSampler(
            exporter,
            slow_threshold_s=float(
                env.get("TRACE_TAIL_SLOW_THRESHOLD_S", "1.0")),
            sample_rate=float(env.get("TRACE_TAIL_SAMPLE_RATE", "0.01")),
        )
        logger.info(
            "OTLP trace export -> %s (tail-sampled: errors + >%.3fs "
            "always, else p=%.3f)", exporter.url,
            installed.slow_threshold_s, installed.sample_rate)
    else:
        logger.info("OTLP trace export -> %s (unsampled)", exporter.url)
    set_exporter(installed)
    return installed


def get_tracer(name: str) -> Tracer:
    """Tracer whose exporter is resolved at each span start, matching the
    reference's OnceValue'd tracer that resolves the provider lazily."""
    return Tracer(name)
