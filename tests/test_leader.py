"""Lease-based leader election (notebook-controller/main.go:91-93,
odh main.go:221-222): two managers, one reconciles; failover on expiry."""

from __future__ import annotations

import time


from kubeflow_tpu.kube import ApiServer
from kubeflow_tpu.kube.client import KubeClient, RestConfig
from kubeflow_tpu.kube.leader import LeaderElector
from kubeflow_tpu.kube.wire import KubeApiWireServer
from kubeflow_tpu.utils.clock import FakeClock


def make_elector(api, ident, clock, **kw):
    return LeaderElector(
        api, lease_name="test-mgr", namespace="system", identity=ident,
        lease_duration_s=15, renew_period_s=10, clock=clock, **kw)


class TestLeaderElection:
    def test_first_candidate_acquires(self):
        api, clock = ApiServer(), FakeClock()
        a = make_elector(api, "mgr-a", clock)
        assert a.try_acquire_or_renew()
        lease = api.get("Lease", "system", "test-mgr")
        assert lease.body["spec"]["holderIdentity"] == "mgr-a"

    def test_second_candidate_blocked_while_lease_fresh(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_elector(api, "mgr-a", clock), make_elector(api, "mgr-b", clock)
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()
        clock.advance(10)  # within the 15s lease: a renews, b still blocked
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()

    def test_failover_after_expiry(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_elector(api, "mgr-a", clock), make_elector(api, "mgr-b", clock)
        assert a.try_acquire_or_renew()
        clock.advance(16)  # a died: no renew for > leaseDuration
        assert b.try_acquire_or_renew(), "stale lease must be taken over"
        lease = api.get("Lease", "system", "test-mgr")
        assert lease.body["spec"]["holderIdentity"] == "mgr-b"
        assert lease.body["spec"]["leaseTransitions"] == 1
        # the deposed leader observes it lost
        assert not a.try_acquire_or_renew()

    def test_graceful_release_enables_immediate_takeover(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_elector(api, "mgr-a", clock), make_elector(api, "mgr-b", clock)
        assert a.try_acquire_or_renew()
        a.release()
        assert b.try_acquire_or_renew(), \
            "released lease (zeroed renewTime) is immediately acquirable"

    def test_election_over_the_wire(self):
        """The same protocol against a real-socket apiserver."""
        api = ApiServer()
        srv = KubeApiWireServer(api).start()
        try:
            clock = FakeClock()
            client_a = KubeClient(RestConfig(server=srv.url))
            client_b = KubeClient(RestConfig(server=srv.url))
            a = make_elector(client_a, "mgr-a", clock)
            b = make_elector(client_b, "mgr-b", clock)
            assert a.try_acquire_or_renew()
            assert not b.try_acquire_or_renew()
            clock.advance(20)
            assert b.try_acquire_or_renew()
        finally:
            srv.stop()

    def test_renew_deadline_abdicates_before_takeover_is_possible(self):
        """client-go semantics (RenewDeadline < LeaseDuration): when the
        apiserver becomes unreachable, the leader must stop leading at the
        renew deadline — STRICTLY BEFORE the lease expires — so there is
        never a moment with two writers."""
        from kubeflow_tpu.kube.errors import ServerError

        class FlakyApi:
            """Delegates to the real store until `fail` is set."""

            def __init__(self, api):
                self._api = api
                self.fail = False

            def __getattr__(self, name):
                target = getattr(self._api, name)
                if not callable(target):
                    return target

                def guarded(*a, **kw):
                    if self.fail:
                        raise ServerError("apiserver unreachable")
                    return target(*a, **kw)

                return guarded

        api = ApiServer()
        flaky = FlakyApi(api)
        started, stopped = [], []
        # lease_duration far above the renew deadline: the rival check
        # below stays deterministic even if CI deschedules this process
        # for tens of seconds
        elector = LeaderElector(
            flaky, "test-mgr", "system", "mgr-a",
            lease_duration_s=30.0, renew_period_s=0.05, retry_period_s=0.05,
            renew_deadline_s=0.4)
        elector.start_background(lambda: started.append(1),
                                 lambda: stopped.append(1))
        try:
            deadline = time.time() + 5
            while not started and time.time() < deadline:
                time.sleep(0.01)
            assert started
            flaky.fail = True
            deadline = time.time() + 5
            while not stopped and time.time() < deadline:
                time.sleep(0.01)
            assert stopped, "unreachable apiserver must trigger abdication"
            # the moment the old leader stopped, the lease (last successful
            # renew seconds ago, duration 30s) is still FRESH: no rival can
            # acquire yet — the single-writer window never overlapped
            rival = LeaderElector(api, "test-mgr", "system", "mgr-b",
                                  lease_duration_s=30.0, renew_period_s=0.05,
                                  retry_period_s=0.05)
            assert not rival.try_acquire_or_renew(), \
                "abdication happened while the lease was still unexpired"
        finally:
            elector.stop()

    def test_paused_old_leader_write_is_fenced(self):
        """The GC-pause classic: a leader deposed while descheduled must
        have its late writes REJECTED, not raced — verify() is the fencing
        check every write under the elector's authority goes through."""
        from kubeflow_tpu.kube.leader import StaleEpochError
        from kubeflow_tpu.kube.shard import FencedApi
        import pytest

        api, clock = ApiServer(), FakeClock()
        a, b = make_elector(api, "mgr-a", clock), make_elector(api, "mgr-b", clock)
        assert a.try_acquire_or_renew()
        assert a.verify() == 0
        # a pauses past the lease; b takes over (epoch bump deposes a)
        clock.advance(16)
        assert b.try_acquire_or_renew()
        assert b.verify() == 1
        # a resumes believing it still leads: its token is still locally
        # "valid", but the lease re-read sees the moved epoch
        assert a.token.valid
        with pytest.raises(StaleEpochError):
            a.verify()
        assert not a.token.valid, "failed verify must latch the invalidation"
        # and every write proxied under a's authority is rejected + counted
        fenced = FencedApi(api, a)
        from kubeflow_tpu.api.types import Notebook
        with pytest.raises(StaleEpochError):
            fenced.create(Notebook.new("late", "default").obj)
        assert fenced.rejected_total == 1
        assert api.try_get("Notebook", "default", "late") is None, \
            "the stale write must never reach the store"
        # the new leader's writes flow
        FencedApi(api, b).create(Notebook.new("fresh", "default").obj)
        assert api.try_get("Notebook", "default", "fresh") is not None

    def test_release_drops_authority_before_the_lease_write(self):
        """release() must invalidate is_leader AND the token BEFORE its
        lease update lands: a successor may acquire the instant that write
        commits, so any of our writes racing past it must already fence."""
        observed = []

        class SpyApi:
            def __init__(self, api):
                self._api = api
                self.elector = None

            def update(self, obj, *a, **kw):
                if obj.kind == "Lease":
                    observed.append(
                        (self.elector.is_leader, self.elector.token.valid))
                return self._api.update(obj, *a, **kw)

            def __getattr__(self, name):
                return getattr(self._api, name)

        api, clock = ApiServer(), FakeClock()
        spy = SpyApi(api)
        a = make_elector(spy, "mgr-a", clock)
        spy.elector = a
        assert a.try_acquire_or_renew()
        observed.clear()  # acquire's own write is legitimately authoritative
        a.release()
        assert observed == [(False, False)], \
            "lease write landed while leadership/token were still live"

    def test_failed_renew_invalidates_token(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_elector(api, "mgr-a", clock), make_elector(api, "mgr-b", clock)
        assert a.try_acquire_or_renew()
        clock.advance(16)
        assert b.try_acquire_or_renew()
        assert not a.try_acquire_or_renew(), "deposed leader must observe loss"
        assert not a.token.valid, \
            "failed renew must invalidate before any worker can write"

    def test_fencing_epoch_stamped_on_every_lease_write(self):
        api, clock = ApiServer(), FakeClock()
        a = make_elector(api, "mgr-a", clock)
        assert a.try_acquire_or_renew()
        spec = api.get("Lease", "system", "test-mgr").body["spec"]
        assert spec["fencingEpoch"] == spec.get("leaseTransitions", 0) == 0
        clock.advance(16)
        b = make_elector(api, "mgr-b", clock)
        assert b.try_acquire_or_renew()
        spec = api.get("Lease", "system", "test-mgr").body["spec"]
        assert spec["fencingEpoch"] == spec["leaseTransitions"] == 1

    def test_background_run_invokes_callbacks(self):
        api = ApiServer()
        started, stopped = [], []
        elector = LeaderElector(api, "test-mgr", "system", "solo",
                                lease_duration_s=0.5, renew_period_s=0.05,
                                retry_period_s=0.05)
        elector.start_background(lambda: started.append(1),
                                 lambda: stopped.append(1))
        deadline = time.time() + 5
        while not started and time.time() < deadline:
            time.sleep(0.01)
        assert started, "elector never started leading"
        # usurp the lease out from under it -> on_stopped must fire
        lease = api.get("Lease", "system", "test-mgr")
        lease.body["spec"]["holderIdentity"] = "other"
        lease.body["spec"]["renewTime"] = "2099-01-01T00:00:00.000000Z"
        api.update(lease)
        deadline = time.time() + 5
        while not stopped and time.time() < deadline:
            time.sleep(0.01)
        elector.stop()
        assert stopped, "losing the lease must invoke on_stopped_leading"
