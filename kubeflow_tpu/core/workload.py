"""Workload rendering: Notebook CR -> StatefulSet(s) + Service(s).

CPU path matches the reference generator behavior
(notebook-controller/controllers/notebook_controller.go:433-552): one
StatefulSet with replicas 0/1 from the stop annotation, label/annotation
propagation with kubectl/notebook filtering, default workdir/port/NB_PREFIX,
optional fsGroup, and a ClusterIP Service 80 -> 8888.

TPU path (spec.tpu) is the new capability: per slice an *indexed* StatefulSet
with replicas = hosts(topology) (0 when stopped — slice-atomic, never
partial), parallel pod management (gang-style startup), google.com/tpu
resource requests, GKE TPU nodeSelectors, and the distributed-runtime env;
plus one shared headless Service giving every worker a stable DNS identity.
"""

from __future__ import annotations

import copy

from ..api.types import Notebook
from ..kube import KubeObject, ObjectMeta
from ..tpu import env as tpuenv
from ..utils.config import CoreConfig
from . import constants as C


def _propagated_annotations(nb: Notebook) -> dict[str, str]:
    """Copy CR annotations to the pod, excluding kubectl/notebook ones
    (reference filter, notebook_controller.go:484-489)."""
    return {
        k: v
        for k, v in nb.metadata.annotations.items()
        if "kubectl" not in k and "notebook" not in k
    }


def _base_pod_template(nb: Notebook, cfg: CoreConfig, sts_name: str) -> dict:
    pod_spec = copy.deepcopy(nb.pod_spec)
    containers = pod_spec.get("containers") or [{"name": nb.name}]
    main = containers[0]
    if not main.get("workingDir"):
        main["workingDir"] = "/home/jovyan"
    if not main.get("ports"):
        main["ports"] = [
            {
                "containerPort": C.DEFAULT_CONTAINER_PORT,
                "name": "notebook-port",
                "protocol": "TCP",
            }
        ]
    prefix = f"/notebook/{nb.namespace}/{nb.name}"
    main["env"] = tpuenv.merge_env(
        main.get("env") or [], [{"name": C.PREFIX_ENV_VAR, "value": prefix}]
    )
    if cfg.add_fsgroup and pod_spec.get("securityContext") is None:
        pod_spec["securityContext"] = {"fsGroup": C.DEFAULT_FSGROUP}
    pod_spec["containers"] = containers

    labels = {
        C.STATEFULSET_LABEL: sts_name,
        C.NOTEBOOK_NAME_LABEL: nb.name,
        C.WORKBENCH_LABEL: "true",
    }
    labels.update(nb.metadata.labels)
    return {
        "metadata": {
            "labels": labels,
            "annotations": _propagated_annotations(nb),
        },
        "spec": pod_spec,
    }


def _render_checkpoint_contract(
    nb: Notebook, cfg: CoreConfig, template: dict, gang: int
) -> None:
    """Checkpoint-sidecar contract on a TPU worker template (rendered only
    when CHECKPOINT_STORE_URI is configured):

    - env the in-pod runtime reads (runtime/checkpoint.py): the store URI
      and the periodic snapshot interval;
    - restore stamping: when `status.sessionState` carries a restore
      intent for this gang (the migrate verb's write-ahead record;
      replicated notebooks key it by flat gang index), the recreated
      pods get CHECKPOINT_RESTORE_URI/_GENERATION so the runtime
      reloads the session instead of starting cold;
    - a pre-stop exec hook (one last snapshot before any pod delete) and
      the downward-API podinfo projection of the checkpoint-requested
      annotation — the file transport CullSignalWatcher polls, so
      periodic + pre-delete + cull snapshots all flow to the store."""
    pod_spec = template["spec"]
    main = pod_spec["containers"][0]
    injected = [
        {"name": C.ENV_CHECKPOINT_STORE_URI,
         "value": cfg.checkpoint_store_uri},
        {"name": C.ENV_CHECKPOINT_INTERVAL_S,
         "value": f"{cfg.checkpoint_interval_s:g}"},
    ]
    session = (nb.status.get("sessionState") or {}).get(str(gang)) or {}
    if session.get("restoreGeneration") is not None:
        injected += [
            {"name": C.ENV_CHECKPOINT_RESTORE_URI,
             "value": session.get("restoreUri")
             or cfg.checkpoint_store_uri},
            {"name": C.ENV_CHECKPOINT_RESTORE_GENERATION,
             "value": str(session["restoreGeneration"])},
        ]
    main["env"] = tpuenv.merge_env(main["env"], injected)
    main.setdefault("lifecycle", {}).setdefault("preStop", {
        "exec": {"command": ["python", "-m",
                             "kubeflow_tpu.runtime.checkpoint",
                             "--pre-stop"]},
    })
    tpuenv.upsert_by_name(pod_spec.setdefault("volumes", []), {
        "name": "podinfo",
        "downwardAPI": {"items": [{
            "path": "checkpoint-requested",
            "fieldRef": {"fieldPath": "metadata.annotations['%s']"
                         % C.ANNOTATION_CHECKPOINT_REQUESTED},
        }]},
    })
    tpuenv.upsert_by_name(main.setdefault("volumeMounts", []), {
        "name": "podinfo", "mountPath": "/etc/podinfo",
    })


def _sts_meta(nb: Notebook, name: str, use_generate_name: bool) -> ObjectMeta:
    if use_generate_name:
        # name-length guard (notebook_controller.go:142-149): controller
        # appends an 11-char hash label, total must fit 63
        meta = ObjectMeta(generate_name="nb-", namespace=nb.namespace)
    else:
        meta = ObjectMeta(name=name, namespace=nb.namespace)
    meta.labels = dict(nb.metadata.labels)
    return meta


def generate_statefulsets(nb: Notebook, cfg: CoreConfig) -> list[KubeObject]:
    """Render the workload STS set: one for CPU notebooks, one per slice for
    TPU notebooks."""
    stopped = C.STOP_ANNOTATION in nb.metadata.annotations
    tpu = nb.tpu

    if tpu is None:
        name = nb.name
        use_generate_name = len(name) > C.MAX_STATEFULSET_NAME_LENGTH
        sts = KubeObject(
            api_version="apps/v1",
            kind="StatefulSet",
            metadata=_sts_meta(nb, name, use_generate_name),
            body={
                "spec": {
                    "replicas": 0 if stopped else 1,
                    "serviceName": nb.name,
                    "selector": {"matchLabels": {C.STATEFULSET_LABEL: name}},
                    "template": _base_pod_template(nb, cfg, name),
                }
            },
        )
        return [sts]

    shape = tpu.validate()
    # slice-scheduler placement intent (core/scheduler.py): gang index ->
    # node-pool assignment, rendered as a nodeSelector so the whole gang
    # co-locates on the pool the scheduler chose
    from .scheduler import placement_of

    placement = placement_of(nb.metadata.annotations)
    rep = nb.replication
    replicas = rep.replicas if rep is not None else 1
    live_rep = nb.status.get("replication") or {}
    primary = int(live_rep.get("primary", 0))
    epoch = int(live_rep.get("epoch", 1))
    out = []
    # replica-major gang order: replica 0's slices first, so gang index
    # g = replica * slices + slice_id lines up with the scheduler's
    # placement keys, the recovery engine's detection indexes, and the
    # sessionState bookkeeping (all keyed by flat gang index)
    for replica in range(replicas):
        for slice_id in range(tpu.slices):
            gang = replica * tpu.slices + slice_id
            name = tpuenv.statefulset_name(
                nb.name, slice_id, tpu.slices, replica)
            # the slice/replica suffix counts against the 52-char guard too
            use_generate_name = len(name) > C.MAX_STATEFULSET_NAME_LENGTH
            template = _base_pod_template(nb, cfg, name)
            template["metadata"]["labels"][C.TPU_SLICE_LABEL] = str(slice_id)
            if rep is not None:
                template["metadata"]["labels"][C.REPLICA_LABEL] = str(replica)
            pod_spec = template["spec"]
            selector = pod_spec.setdefault("nodeSelector", {})
            selector[C.GKE_TPU_ACCELERATOR_LABEL] = \
                shape.accelerator.gke_label
            selector[C.GKE_TPU_TOPOLOGY_LABEL] = shape.topology
            assigned_pool = (placement.get(str(gang)) or {}).get("pool")
            if assigned_pool:
                selector[C.GKE_NODEPOOL_LABEL] = assigned_pool
            main = pod_spec["containers"][0]
            resources = main.setdefault("resources", {})
            for kind in ("requests", "limits"):
                resources.setdefault(kind, {})[C.TPU_RESOURCE] = \
                    str(shape.chips_per_host)
            main["env"] = tpuenv.merge_env(
                main["env"],
                tpuenv.tpu_env_vars(nb.name, shape, slice_id, tpu.slices,
                                    replica))
            if rep is not None:
                # boot-time hints only: the authoritative role is the
                # status.replication pointer + the store's write fence.
                # A promotion flip re-renders these, but running pods
                # keep their boot env — a demoted primary that trusts
                # its stale env hits StaleWriterError at the store.
                main["env"] = tpuenv.merge_env(main["env"], [
                    {"name": C.ENV_REPLICA_INDEX, "value": str(replica)},
                    {"name": C.ENV_REPLICATION_ROLE,
                     "value": C.ROLE_PRIMARY if replica == primary
                     else C.ROLE_FOLLOWER},
                    {"name": C.ENV_REPLICATION_EPOCH, "value": str(epoch)},
                ])
            if cfg.checkpoint_store_uri:
                _render_checkpoint_contract(nb, cfg, template, gang)
            sts = KubeObject(
                api_version="apps/v1",
                kind="StatefulSet",
                metadata=_sts_meta(nb, name, use_generate_name),
                body={
                    "spec": {
                        # slice-atomic: all hosts or none — partial slices
                        # can never run a collective, so 0 is the only
                        # other state
                        "replicas": 0 if stopped else shape.num_hosts,
                        "serviceName": tpuenv.headless_service_name(nb.name),
                        "podManagementPolicy": "Parallel",
                        "selector": {
                            "matchLabels": {C.STATEFULSET_LABEL: name}},
                        "template": template,
                    }
                },
            )
            sts.metadata.labels[C.NOTEBOOK_NAME_LABEL] = nb.name
            if rep is not None:
                sts.metadata.labels[C.REPLICA_LABEL] = str(replica)
            out.append(sts)
    return out


def generate_service(nb: Notebook) -> KubeObject:
    """ClusterIP Service 80 -> notebook port, name http-notebook (Istio-
    compatible port naming), selecting the (first) statefulset's pods
    (notebook_controller.go:525-552).  For TPU notebooks this fronts worker
    0, where the JupyterLab server runs.  Replicated notebooks front the
    CURRENT primary's worker 0: a promotion flips status.replication.primary
    and the very next reconcile repoints this selector — user traffic
    follows the failover with no pod restarts in between."""
    containers = nb.pod_spec.get("containers") or []
    port = C.DEFAULT_CONTAINER_PORT
    if containers and containers[0].get("ports"):
        port = int(containers[0]["ports"][0].get("containerPort", port))
    tpu = nb.tpu
    primary = 0
    if nb.replication is not None:
        primary = int((nb.status.get("replication") or {}).get("primary", 0))
    sts0 = tpuenv.statefulset_name(
        nb.name, 0, tpu.slices if tpu else 1, primary)
    return KubeObject(
        api_version="v1",
        kind="Service",
        metadata=ObjectMeta(name=nb.name, namespace=nb.namespace),
        body={
            "spec": {
                "type": "ClusterIP",
                "selector": {C.STATEFULSET_LABEL: sts0},
                "ports": [
                    {
                        "name": "http-notebook",
                        "port": C.DEFAULT_SERVING_PORT,
                        "targetPort": port,
                        "protocol": "TCP",
                    }
                ],
            }
        },
    )


def generate_headless_service(nb: Notebook) -> KubeObject:
    """Headless Service over ALL workers of ALL slices: gives each pod the
    stable {pod}.{svc}.{ns} DNS name that TPU_WORKER_HOSTNAMES and the JAX
    coordinator address rely on.  The TPU-native analog of the reference's
    plain Service (SURVEY.md §5 'Distributed communication backend')."""
    return KubeObject(
        api_version="v1",
        kind="Service",
        metadata=ObjectMeta(
            name=tpuenv.headless_service_name(nb.name), namespace=nb.namespace
        ),
        body={
            "spec": {
                "clusterIP": "None",
                "selector": {C.NOTEBOOK_NAME_LABEL: nb.name},
                # workers must resolve worker 0 before any pod can become
                # Ready — without this, gang startup deadlocks on DNS
                "publishNotReadyAddresses": True,
                "ports": [
                    {
                        "name": "jax-coordinator",
                        "port": tpuenv.JAX_COORDINATOR_PORT,
                        "targetPort": tpuenv.JAX_COORDINATOR_PORT,
                        "protocol": "TCP",
                    }
                ],
            }
        },
    )


def generate_virtual_service(nb: Notebook, cfg: CoreConfig) -> KubeObject:
    """Istio VirtualService under USE_ISTIO
    (notebook_controller.go:558-699): route
    /notebook/{ns}/{name}/ through the configured gateway to the Service,
    honoring the rewrite/headers annotations."""
    prefix = f"/notebook/{nb.namespace}/{nb.name}/"
    rewrite = nb.metadata.annotations.get(C.ANNOTATION_REWRITE_URI, "")
    rewrite_uri = rewrite if rewrite.strip() else prefix
    http_route: dict = {
        "match": [{"uri": {"prefix": prefix}}],
        "rewrite": {"uri": rewrite_uri},
        "route": [
            {
                "destination": {
                    "host": f"{nb.name}.{nb.namespace}.svc.{cfg.cluster_domain}",
                    "port": {"number": C.DEFAULT_SERVING_PORT},
                }
            }
        ],
        "timeout": "300s",
    }
    headers = nb.metadata.annotations.get(C.ANNOTATION_HEADERS_REQUEST_SET, "")
    if headers.strip():
        import json

        try:
            http_route["headers"] = {"request": {"set": json.loads(headers)}}
        except ValueError:
            pass  # malformed annotation ignored, as in the reference
    return KubeObject(
        api_version="networking.istio.io/v1alpha3",
        kind="VirtualService",
        metadata=ObjectMeta(
            name=f"notebook-{nb.namespace}-{nb.name}", namespace=nb.namespace
        ),
        body={
            "spec": {
                "hosts": [cfg.istio_host],
                "gateways": [cfg.istio_gateway],
                "http": [http_route],
            }
        },
    )
