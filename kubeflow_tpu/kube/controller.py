"""Controller runtime: reconcilers, watch wiring, workqueue, manager.

Mirrors the controller-runtime model the reference is built on —
level-triggered reconcilers keyed by namespace/name, For/Owns/Watches source
wiring with predicates and request mappers
(notebook-controller/controllers/notebook_controller.go:777-826), and a
manager that runs every registered controller
(notebook-controller/main.go:58-148).  Execution is deterministic and
single-threaded by default (`run_until_idle`), which replaces envtest's
eventually-consistent goroutine loop with exact test semantics; standalone
operation runs a pool of WORKQUEUE_WORKERS worker threads with strict
per-key serialization (controller-runtime workqueue semantics — an
in-flight key parks instead of double-dispatching), and `run_until_idle`
drives the same pool batch-wise so threaded soaks stay FakeClock-exact.
Reconcilers read through the manager's indexed informer cache
(kube/cache.py) rather than live api.list scans.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Protocol

from ..utils import invariants, tracing
from ..utils.clock import Clock
from ..utils.flightrecorder import FlightRecorder
from ..utils.metrics import Registry
from .cache import InformerCache
from .errors import GoneError
from .meta import KubeObject
from .store import ApiServer, EventType, WatchEvent

logger = logging.getLogger("kubeflow_tpu.kube")

# every reconcile attempt runs under a root span from this tracer (noop
# until an exporter is installed — utils.tracing.set_exporter)
_TRACER = tracing.get_tracer("kubeflow_tpu.kube.manager")


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0  # seconds


class Reconciler(Protocol):
    def reconcile(self, req: Request) -> Result: ...


Predicate = Callable[[WatchEvent], bool]
Mapper = Callable[[KubeObject], list[Request]]

# metadata keys the server rewrites on every store commit; a delta confined
# to these (plus status) is a self-inflicted status write, not user intent
_SERVER_META_KEYS = ("resourceVersion", "managedFields", "generation")


def is_status_only_update(ev: WatchEvent) -> bool:
    """True for MODIFIED events whose old→new delta is confined to `status`
    and server-managed metadata.  Only decidable when the event carries its
    pre-update state (`prev` — the in-memory watch cache provides it; a
    real-cluster informer does not, and the predicate then passes
    everything, which is merely chatty, never incorrect)."""
    if ev.type is not EventType.MODIFIED or ev.prev is None:
        return False

    def strip(obj: KubeObject) -> dict:
        d = obj.to_dict()
        d.pop("status", None)
        meta = d.get("metadata")
        if isinstance(meta, dict):
            for k in _SERVER_META_KEYS:
                meta.pop(k, None)
        return d

    return strip(ev.prev) == strip(ev.obj)


def suppress_status_only(ev: WatchEvent) -> bool:
    """for_predicate that drops self-inflicted status-only updates: a
    controller that writes its primary's status must not be re-triggered by
    that very write, or a converged fleet never reaches a zero-reconcile
    steady state.  Only correct on kinds whose status THIS manager's
    controllers write (the Notebook CR) — an owned workload's status
    (StatefulSet readyReplicas) is data-plane truth the reconciler needs,
    and those arrive via Owns/Watches wiring, not the for_kind path."""
    return not is_status_only_update(ev)


@dataclass
class WatchSpec:
    kind: str
    mapper: Mapper
    predicate: Optional[Predicate] = None


@dataclass
class _Registration:
    name: str
    reconciler: Reconciler
    for_kind: str
    owns: list[str] = field(default_factory=list)
    watches: list[WatchSpec] = field(default_factory=list)
    max_retries: int = 5
    # event filter on the primary kind (controller-runtime WithEventFilter
    # scoped to For); suppress_status_only is the canonical instance
    for_predicate: Optional[Predicate] = None


@dataclass(order=True)
class _Delayed:
    due: float
    reg_name: str = field(compare=False)
    request: Request = field(compare=False)
    # True for rate-limited retries (error backoff / Result.requeue): these
    # are part of "draining the queue" and run_until_idle may advance a fake
    # clock over them; requeue_after waits are scheduled work and are NOT
    # auto-advanced (tests drive those with advance())
    retry: bool = field(default=False, compare=False)
    # when the item entered the workqueue system (clock time); retries stamp
    # at schedule time so the backoff wait shows up in
    # workqueue_queue_duration_seconds, while requeue_after schedules (0.0)
    # stamp at promotion — a timer wait is not queueing
    enqueued_at: float = field(default=0.0, compare=False)


# -- workqueue rate limiting ---------------------------------------------------
# The controller-runtime default workqueue limiter:
# MaxOfRateLimiter(ItemExponentialFailureRateLimiter(5ms, 1000s),
#                  BucketRateLimiter(10 qps, 100 burst)).  `when(item)`
# charges the item and returns the delay before it may run again;
# `forget(item)` resets its failure history on success.


class ItemExponentialBackoff:
    """Per-item exponential backoff with bounded jitter.

    delay = min(base * 2^failures, cap), then scaled by a seeded jitter in
    [1, 1+jitter) — deterministic for a given seed, so tests can assert
    exact bounds; jitter decorrelates retry herds in threaded mode."""

    def __init__(self, base_s: float = 0.005, cap_s: float = 1000.0,
                 jitter: float = 0.1, seed: int = 0) -> None:
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._failures: dict[object, int] = {}
        self._lock = invariants.tracked(
            threading.Lock(), "ItemExponentialBackoff._lock")

    def when(self, item) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        delay = min(self.base_s * (2 ** n), self.cap_s)
        if self.jitter > 0:
            delay *= 1.0 + self.jitter * self._rng.random()
        return delay

    def forget(self, item) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_failures(self, item) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter:
    """Token-bucket overall limiter on an injectable clock
    (client-go flowcontrol; reservations may drive tokens negative, so a
    burst of retries spreads out at 1/qps)."""

    def __init__(self, qps: float = 10.0, burst: int = 100,
                 clock: Optional[Clock] = None) -> None:
        self.qps = qps
        self.burst = max(burst, 1)
        self.clock = clock or Clock()
        self._tokens = float(self.burst)
        self._last = self.clock.now()
        self._lock = invariants.tracked(
            threading.Lock(), "BucketRateLimiter._lock")

    def when(self, item) -> float:
        if self.qps <= 0:
            return 0.0
        with self._lock:
            now = self.clock.now()
            self._tokens = min(float(self.burst),
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= 1.0  # reserve (may go negative)
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.qps

    def forget(self, item) -> None:
        pass

    def num_failures(self, item) -> int:
        return 0


class MaxOfRateLimiter:
    """The worst (longest) answer of its children — controller-runtime's
    DefaultControllerRateLimiter composition."""

    def __init__(self, *limiters) -> None:
        self.limiters = limiters

    def when(self, item) -> float:
        return max(rl.when(item) for rl in self.limiters)

    def forget(self, item) -> None:
        for rl in self.limiters:
            rl.forget(item)

    def num_failures(self, item) -> int:
        return max(rl.num_failures(item) for rl in self.limiters)


def default_rate_limiter(
    clock: Optional[Clock] = None,
    base_s: float = 0.005,
    cap_s: float = 1000.0,
    qps: float = 10.0,
    burst: int = 100,
    jitter: float = 0.1,
    seed: int = 0,
) -> MaxOfRateLimiter:
    """The workqueue limiter Manager installs by default; knobs map to
    CoreConfig.workqueue_* (utils.config) so deployments can tune them."""
    return MaxOfRateLimiter(
        ItemExponentialBackoff(base_s=base_s, cap_s=cap_s, jitter=jitter,
                               seed=seed),
        BucketRateLimiter(qps=qps, burst=burst, clock=clock),
    )


class _WatchSession:
    """The manager's droppable watch connection.  Tracks the newest
    resourceVersion delivered so a fault-injected stream drop
    (ApiServer.drop_watch_connections) can resume exactly where it left
    off via subscribe(since_rv); if the history window was compacted away
    (410 Gone) it reconnects live-only and RELISTS — enqueueing every
    primary object, the client-go reflector's relist in controller terms.

    The session registers FILTERED: it asks the apiserver only for the
    kinds some registered controller watches (`kinds`, kept current by
    Manager.register/unregister via update_watch_kinds), so an event on
    an uninteresting kind never invokes this callback at all."""

    def __init__(self, mgr: "Manager") -> None:
        self.mgr = mgr
        self.last_rv = 0
        self.connected = True
        self.drops = 0
        self.relists = 0
        # current kind filter (None until first registration: nothing is
        # interesting yet, but resume semantics want the full stream shape
        # only for watched kinds anyway)
        self.kinds: list[str] = []

    def __call__(self, ev: WatchEvent) -> None:
        rv = ev.obj.metadata.resource_version
        if isinstance(rv, int) and rv > self.last_rv:
            self.last_rv = rv
        self.mgr._on_event(ev)

    def on_watch_dropped(self) -> None:
        # noticed lazily, as a real client notices a dead stream: the
        # manager reconnects at its next processing step, so events landing
        # in between form a genuine gap the resume protocol must cover
        self.drops += 1
        self.connected = False

    def set_kinds(self, kinds: list[str]) -> None:
        self.kinds = list(kinds)
        update = getattr(self.mgr.api, "update_watch_kinds", None)
        if update is not None and self.connected:
            update(self, self.kinds)

    def reconnect(self) -> None:
        api = self.mgr.api
        try:
            api.subscribe(self, since_rv=self.last_rv, kinds=self.kinds)
        except GoneError:
            # resume window compacted away (410): reconnect live and
            # relist so no state transition is missed (level-triggered
            # reconcilers re-derive everything from current state).  The
            # relist itself is recovery machinery, not client traffic —
            # exempt from an active fault plan
            api.subscribe(self, kinds=self.kinds)
            self.relists += 1
            exempt = getattr(api, "fault_exempt", None)
            if exempt is not None:
                with exempt():
                    self.mgr.enqueue_all()
            else:
                self.mgr.enqueue_all()
        self.connected = True


class Manager:
    """Runs registered controllers against an ApiServer.

    Tests drive it with `run_until_idle()` (drains the workqueue, honoring
    requeue-after via the injected clock when `advance_clock=True`);
    standalone mode uses `start()` which spins `workers` worker threads.

    Parallelism follows controller-runtime's workqueue contract: up to
    `workers` requests process concurrently, but never two for the same
    (controller, request) key — an event for an in-flight key parks in the
    dirty set and re-queues when the running reconcile completes.  Popping
    is round-robin across controllers so one hot controller cannot starve
    the rest.  `workers` defaults to the WORKQUEUE_WORKERS env var (1 when
    unset); `run_until_idle` uses the same pool, processing per-batch with
    a barrier so FakeClock advancement stays single-threaded.
    """

    def __init__(self, api: ApiServer, clock: Optional[Clock] = None,
                 rate_limiter=None, registry: Optional[Registry] = None,
                 flight_recorder: Optional[FlightRecorder] = None,
                 workers: Optional[int] = None,
                 cache: Optional[InformerCache] = None,
                 key_filter=None) -> None:
        self.api = api
        self.clock = clock or Clock()
        # sharded control plane (kube/shard.py): admit only requests this
        # replica owns.  Checked at enqueue AND re-checked at dispatch, so
        # a key that moved away while queued is dropped, not reconciled.
        self._key_filter = key_filter
        if workers is None:
            try:
                workers = int(os.environ.get("WORKQUEUE_WORKERS", "") or 1)
            except ValueError:
                workers = 1
        self.workers = max(1, workers)
        # bounded in-process history of completed reconcile attempts, fed
        # with each attempt's finished root span (/debug/reconciles reads it)
        self.flight_recorder = flight_recorder or FlightRecorder()
        # optional fleet observers (build_manager wires them): the SLO
        # engine receives every completed AttemptRecord (exemplar latching
        # for burn alerts — utils/slo.py), the continuous profiler hangs
        # here so /debug/profile can reach it, the lifecycle ledger folds
        # every attempt into its notebook's stage partition
        # (utils/lifecycle.py), and the TSDB hangs here for /debug/timeline
        self.slo_engine = None
        self.profiler = None
        self.lifecycle = None
        self.tsdb = None
        # tenant metering ledger (utils/metering.py): receives per-tenant
        # workqueue dispatch attribution and the completed-attempt stream
        self.metering = None
        # causal diagnosis engine (utils/diagnosis.py): mines the attempt
        # stream for discrete evidence (faults, promotions, recoveries)
        self.diagnosis = None
        # replica identity for lifecycle attribution: a sharded fleet sets
        # this to the shard id so a manager change between consecutive
        # attempts of one notebook reads as handoff/adoption wait
        self.manager_id = ""
        self._limiter = rate_limiter or default_rate_limiter(self.clock)
        self._registrations: list[_Registration] = []
        self._lock = invariants.tracked(
            threading.Lock(), "Manager._lock")
        # per-controller FIFO deques, popped round-robin (fairness across
        # registrations); _queued is the dirty set — the single source of
        # truth for "this key has pending work"
        self._queues: dict[str, deque[tuple[str, Request]]] = {}
        self._queued: set[tuple[str, Request]] = set()
        # keys currently being reconciled (per-key serialization): an
        # event for one of these parks in _queued and re-queues on _done
        self._processing: set[tuple[str, Request]] = set()
        # clock time each in-flight key started processing, feeding
        # workqueue_longest_running_processor_seconds
        self._inflight_started: dict[tuple[str, Request], float] = {}
        self._rr_cursor = 0  # round-robin position over registrations
        self._delayed: list[_Delayed] = []
        self._retries: dict[tuple[str, Request], int] = {}
        self._errors: list[tuple[str, Request, BaseException]] = []
        # per-controller observability (scraped by core.metrics):
        # retries scheduled, last backoff delay, errors dropped
        self._retry_totals: dict[str, int] = {}
        self._last_backoff: dict[str, float] = {}
        # controller-runtime's canonical reconcile/workqueue telemetry, all
        # timed off the injected clock so FakeClock tests see exact values.
        # core.metrics.NotebookMetrics concatenates this registry into the
        # /metrics exposition when a manager is attached.
        self.metrics_registry = registry or Registry()
        self.reconcile_total = self.metrics_registry.counter(
            "controller_runtime_reconcile_total",
            "Total number of reconciliations per controller",
            labels=("controller", "result"))
        self.reconcile_time = self.metrics_registry.histogram(
            "controller_runtime_reconcile_time_seconds",
            "Length of time per reconciliation per controller",
            labels=("controller",))
        self.queue_duration = self.metrics_registry.histogram(
            "workqueue_queue_duration_seconds",
            "How long a request stays in the workqueue (retry backoff "
            "included) before processing starts",
            labels=("controller",))
        self.work_duration = self.metrics_registry.histogram(
            "workqueue_work_duration_seconds",
            "How long processing a request from the workqueue takes",
            labels=("controller",))
        # control-plane reaction latency, the NotebookOS headline number:
        # the clock delta from the watch event that caused an enqueue to
        # the moment its reconcile starts.  Only event-caused enqueues are
        # stamped (resyncs and retry promotions are not reactions); the
        # first cause wins while a key stays queued.
        self.event_to_reconcile = self.metrics_registry.histogram(
            "notebook_event_to_reconcile_seconds",
            "Latency from the enqueue-cause watch event to reconcile start",
            labels=("controller",),
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0,
                     120.0))
        # per-key cause stamps: (clock time, monotonic wall time) of the
        # event that put the key in the queue
        self._cause_stamps: dict[tuple[str, Request], tuple[float, float]] = {}
        # per-key tenant stamps (owning namespace at enqueue), feeding the
        # metering ledger's per-tenant dispatch attribution at _pop
        self._tenant_stamps: dict[tuple[str, Request], str] = {}
        # cause clock-time carried from _pop to the attempt's root span
        # (per-key serialization guarantees no concurrent writer per key)
        self._attempt_cause: dict[tuple[str, Request], float] = {}
        # exact wall-clock samples for percentile reporting (FakeClock runs
        # collapse the injected-clock delta to ~0, so the loadtest reads
        # real reaction time from here); bounded for long-lived managers
        self._event_latency: deque[float] = deque(maxlen=1 << 18)
        # indexed informer cache: the reconcilers' read path (hot-path
        # lookups go through registered indexes instead of api.list scans);
        # subscribes to the same watch stream as the manager, BEFORE the
        # manager's own session so an event's cache update is visible by
        # the time its reconcile request can possibly run
        self.cache = cache if cache is not None else \
            InformerCache(api, registry=self.metrics_registry)
        # enqueue timestamps feeding workqueue_queue_duration_seconds
        self._enqueued_at: dict[tuple[str, Request], float] = {}
        # one trace per retry chain: trace id held until the request
        # succeeds, schedules itself (requeue_after), or is dropped;
        # attempt numbers ride along as span attributes
        self._trace_ids: dict[tuple[str, Request], str] = {}
        self._attempt_seq: dict[tuple[str, Request], int] = {}
        self._stop = threading.Event()
        self._started = False
        self._threads: list[threading.Thread] = []
        if hasattr(api, "subscribe"):
            # in-memory ApiServer: a resumable session that survives
            # injected watch-stream drops (kube.faults), registered with an
            # (initially empty) kind filter that register() keeps current
            self._watch_session: Optional[_WatchSession] = _WatchSession(self)
            api.watch(self._watch_session, kinds=[])
        else:
            # KubeClient: its reflector informers own drop/relist recovery
            # and are already per-kind streams
            self._watch_session = None
            api.watch(self._on_event)

    def set_rate_limiter(self, rate_limiter) -> None:
        """Swap the workqueue rate limiter (see default_rate_limiter);
        in-flight failure history is dropped with the old limiter."""
        self._limiter = rate_limiter

    # -- registration ---------------------------------------------------------
    def register(
        self,
        name: str,
        reconciler: Reconciler,
        for_kind: str,
        owns: Optional[list[str]] = None,
        watches: Optional[list[WatchSpec]] = None,
        max_retries: int = 5,
        for_predicate: Optional[Predicate] = None,
    ) -> None:
        self._registrations.append(
            _Registration(
                name=name,
                reconciler=reconciler,
                for_kind=for_kind,
                owns=owns or [],
                watches=watches or [],
                max_retries=max_retries,
                for_predicate=for_predicate,
            )
        )
        with self._lock:
            self._queues.setdefault(name, deque())
        # widen (never replays) the session's kind filter to cover the new
        # controller's For/Owns/Watches set — a kind no controller watches
        # never reaches _on_event at all
        if self._watch_session is not None:
            self._watch_session.set_kinds(self.watched_kinds())

    def unregister(self, name: str) -> None:
        """Remove a controller and drop its queued/delayed work.  An
        in-flight reconcile for it finishes first (the worker holds no
        lock across reconciles, so the next _pop simply won't see it)."""
        with self._lock:
            self._registrations = [
                r for r in self._registrations if r.name != name]
            self._queues.pop(name, None)
            self._queued = {k for k in self._queued if k[0] != name}
            self._delayed = [d for d in self._delayed if d.reg_name != name]
            # retry budgets AND rate-limiter history die with the controller
            # — a later registration under the same name starts fresh, not
            # mid-backoff
            dropped = [k for k in self._retries if k[0] == name]
            self._retries = {k: v for k, v in self._retries.items()
                             if k[0] != name}
            for d in (self._enqueued_at, self._trace_ids, self._attempt_seq,
                      self._cause_stamps, self._attempt_cause,
                      self._tenant_stamps):
                for k in [k for k in d if k[0] == name]:
                    del d[k]
        for k in dropped:
            self._limiter.forget(k)
        if self._watch_session is not None:
            self._watch_session.set_kinds(self.watched_kinds())

    # -- event -> requests ----------------------------------------------------
    def _on_event(self, ev: WatchEvent) -> None:
        # one cause stamp per delivery: the (clock, wall) instant of the
        # event whose requests are about to enqueue, feeding the
        # event->reconcile-start reaction-latency metric
        cause = (self.clock.now(), time.monotonic())
        for reg in self._registrations:
            for req in self._requests_for(reg, ev):
                self._enqueue(reg.name, req, cause=cause)

    def _requests_for(self, reg: _Registration, ev: WatchEvent) -> list[Request]:
        obj = ev.obj
        if obj.kind == reg.for_kind:
            if reg.for_predicate is not None and not reg.for_predicate(ev):
                return []
            return [Request(obj.namespace, obj.name)]
        if obj.kind in reg.owns:
            ref = obj.metadata.controller_owner()
            if ref is not None and ref.kind == reg.for_kind:
                return [Request(obj.namespace, ref.name)]
            return []
        out: list[Request] = []
        for spec in reg.watches:
            if spec.kind != obj.kind:
                continue
            if spec.predicate is not None and not spec.predicate(ev):
                continue
            out.extend(spec.mapper(obj))
        return out

    def _enqueue(self, reg_name: str, req: Request,
                 enqueued_at: Optional[float] = None,
                 cause: Optional[tuple[float, float]] = None) -> None:
        if self._key_filter is not None and \
                not self._key_filter(req.namespace, req.name):
            return  # not ours: rejected before the queue, not a mutation
        invariants.yield_point("queue.add", (reg_name, req.namespace,
                                             req.name))
        with self._lock:
            key = (reg_name, req)
            if key in self._queued:
                return
            queue = self._queues.get(reg_name)
            if queue is None:
                return  # controller unregistered; drop
            self._queued.add(key)
            # per-key serialization: a key being processed is PARKED (dirty
            # only) — _done re-queues it when the running reconcile ends,
            # so no worker ever processes the same key concurrently
            if key not in self._processing:
                queue.append(key)
            self._enqueued_at.setdefault(
                key,
                self.clock.now() if enqueued_at is None else enqueued_at)
            if cause is not None:
                # first cause wins while the key stays dirty: the reaction
                # latency is measured from the event the fleet REACTED to
                self._cause_stamps.setdefault(key, cause)
            # tenant stamp rides next to the cause stamp: the owning
            # namespace at enqueue time, attributing this key's queue wait
            # and reaction latency to its tenant at dispatch (_pop)
            self._tenant_stamps.setdefault(key, req.namespace)

    def enqueue(self, reg_name: str, req: Request) -> None:
        """Manual enqueue (tests, resync ticks)."""
        self._enqueue(reg_name, req)

    def watched_kinds(self) -> list[str]:
        """Every kind any controller watches — the informer set a real-cluster
        backend must stream (controller-runtime derives the same from
        For/Owns/Watches wiring)."""
        kinds: set[str] = set()
        for reg in self._registrations:
            kinds.add(reg.for_kind)
            kinds.update(reg.owns)
            kinds.update(spec.kind for spec in reg.watches)
        return sorted(kinds)

    def enqueue_all(self, reg_name: Optional[str] = None,
                    exclude_kinds: tuple = ()) -> None:
        """Resync: enqueue every existing primary object (informer
        re-list).  Reads the informer cache — key materialization only,
        no apiserver round trip, no per-object deepcopy — and the dirty
        set dedupes against work already queued or in flight.
        `exclude_kinds` skips controllers whose For-kind is listed —
        the shard adoption path covers those via `enqueue_keys` and
        only needs the sweep for the rest."""
        if self.cache is not None:
            self.cache.ensure_connected()
        for reg in self._registrations:
            if reg_name is not None and reg.name != reg_name:
                continue
            if reg.for_kind in exclude_kinds:
                continue
            if self.cache is not None:
                keys = self.cache.keys(reg.for_kind)
            else:
                keys = [(o.namespace, o.name)
                        for o in self.api.list(reg.for_kind)]
            for ns, name in keys:
                self._enqueue(reg.name, Request(ns, name))

    def enqueue_keys(self, kind: str,
                     keys: Iterable[tuple[str, str]]) -> None:
        """Batched enqueue of specific primary keys for every controller
        whose For-kind is `kind` — ONE lock acquisition and ONE schedule
        point for the whole batch.  The shard adoption path uses this: a
        membership commit can grant thousands of keys at once, and the
        per-key _enqueue walk (lock + yield point each) was measurable
        wall time in the 10k+ fleet sweeps."""
        reqs = [Request(ns, name) for ns, name in keys]
        if self._key_filter is not None:
            reqs = [r for r in reqs
                    if self._key_filter(r.namespace, r.name)]
        reg_names = [r.name for r in self._registrations
                     if r.for_kind == kind]
        if not reqs or not reg_names:
            return
        invariants.yield_point("queue.add", (kind, "batch", len(reqs)))
        now = self.clock.now()
        with self._lock:
            for reg_name in reg_names:
                queue = self._queues.get(reg_name)
                if queue is None:
                    continue
                for req in reqs:
                    key = (reg_name, req)
                    if key in self._queued:
                        continue
                    self._queued.add(key)
                    if key not in self._processing:
                        queue.append(key)
                    self._enqueued_at.setdefault(key, now)
                    self._tenant_stamps.setdefault(key, req.namespace)

    def has_pending_work(self) -> bool:
        """Structural-idleness probe for fleet settle loops: anything
        queued, parked in flight, or waiting in delayed retry means a
        run_until_idle pass could still do work.  O(1) under the lock —
        cheap enough to ask once per replica per settle round, which is
        what lets an idle shard be skipped entirely."""
        with self._lock:
            return bool(self._queued or self._processing or self._delayed)

    def pending_count(self) -> int:
        """Outstanding work items (queued + in flight + delayed) — the
        scale factor for drain-loop livelock caps: a shard that owns N
        keys legitimately runs O(N) reconciles in one drain, so a flat
        iteration cap misreads initial convergence at fleet scale as a
        livelock."""
        with self._lock:
            return len(self._queued) + len(self._processing) + \
                len(self._delayed)

    # -- execution ------------------------------------------------------------
    def _pop(self) -> Optional[tuple[str, Request]]:
        invariants.yield_point("queue.pop", None)
        with self._lock:
            # fairness: rotate over registrations so one chatty controller
            # cannot starve the others' queues
            names = [r.name for r in self._registrations]
            key = None
            for off in range(len(names)):
                name = names[(self._rr_cursor + off) % len(names)]
                queue = self._queues.get(name)
                if queue:
                    key = queue.popleft()
                    self._rr_cursor = (self._rr_cursor + off + 1) % len(names)
                    break
            if key is None:
                return None
            self._queued.discard(key)
            self._processing.add(key)
            self._inflight_started[key] = self.clock.now()
            enqueued_at = self._enqueued_at.pop(key, None)
            cause = self._cause_stamps.pop(key, None)
            tenant = self._tenant_stamps.pop(key, key[1].namespace)
            tid = self._trace_ids.get(key, "")
            if cause is not None:
                # ride the cause clock-time to _process_item so the
                # lifecycle ledger can anchor the notebook's event->ready
                # window at the event the fleet reacted to
                self._attempt_cause[key] = cause[0]
        e2r_s = 0.0
        if cause is not None:
            # event -> reconcile-start: the injected-clock delta feeds the
            # deterministic histogram; the wall-clock delta feeds the exact
            # percentile samples the loadtest reports
            e2r_s = max(self.clock.now() - cause[0], 0.0)
            self.event_to_reconcile.labels(key[0]).observe(e2r_s)
            self._event_latency.append(
                max(time.monotonic() - cause[1], 0.0))
        queue_s = 0.0
        if enqueued_at is not None:
            # a retry's queue wait belongs to its live retry chain: exemplar
            # the observation with that trace so a fat queue-duration bucket
            # links straight to the backoff timeline that caused it
            queue_s = max(self.clock.now() - enqueued_at, 0.0)
            self.queue_duration.labels(key[0]).observe(
                queue_s,
                exemplar={"trace_id": tid} if tid else None)
        if self.metering is not None and \
                (cause is not None or enqueued_at is not None):
            try:
                # same clock-domain values the histograms above observed,
                # attributed to the owning tenant
                self.metering.observe_dispatch(tenant, queue_s, e2r_s)
            except Exception:  # noqa: BLE001 — observability must never
                # take the dispatch path down with it
                logger.exception("metering rejected a dispatch")
        return key

    def _done(self, key: tuple[str, Request]) -> None:
        """Finish processing `key`: release the per-key slot and re-queue
        it when events parked on it while it ran."""
        invariants.yield_point("queue.done", (key[0], key[1].namespace,
                                              key[1].name))
        with self._lock:
            self._processing.discard(key)
            self._inflight_started.pop(key, None)
            if key in self._queued:
                queue = self._queues.get(key[0])
                if queue is not None:
                    queue.append(key)
                else:
                    self._queued.discard(key)

    def _promote_delayed(self) -> None:
        now = self.clock.now()
        with self._lock:
            due = [d for d in self._delayed if d.due <= now]
            self._delayed = [d for d in self._delayed if d.due > now]
        for d in due:
            self._enqueue(d.reg_name, d.request,
                          enqueued_at=d.enqueued_at or None)

    def _ensure_sources(self) -> None:
        """Lazily reconnect dropped watch sessions (the cache FIRST, so a
        reconcile popped right after never reads state older than the event
        stream that will re-trigger it)."""
        if self.cache is not None:
            self.cache.ensure_connected()
        if self._watch_session is not None and \
                not self._watch_session.connected:
            self._watch_session.reconnect()

    def _process_one(self) -> bool:
        self._ensure_sources()
        self._promote_delayed()
        item = self._pop()
        if item is None:
            return False
        try:
            self._process_item(item)
        finally:
            self._done(item)
        return True

    def _process_item(self, item: tuple[str, Request]) -> None:
        """Reconcile one popped request (the caller owns _pop/_done)."""
        reg_name, req = item
        reg = next((r for r in self._registrations if r.name == reg_name),
                   None)
        if reg is None:
            return  # unregistered while queued: drop the item
        if self._key_filter is not None and \
                not self._key_filter(req.namespace, req.name):
            # ownership moved while the key sat queued (shard handoff):
            # the new owner adopts it; dispatching here would be a
            # double-reconcile in the new epoch
            return

        def alive() -> bool:
            # unregister() may run DURING the reconcile; its queue/retry
            # cleanup must not be undone by this reconcile's bookkeeping —
            # identity check, so a same-name re-registration stays clean
            with self._lock:
                return any(r is reg for r in self._registrations)

        # attempt numbering + trace identity: every attempt of one retry
        # chain (error backoff / requeue=True) shares a trace id, so a
        # chaos-soak trace shows which injected fault hit which attempt
        with self._lock:
            attempt = self._attempt_seq.get(item, 0) + 1
            self._attempt_seq[item] = attempt
            cause_ts = self._attempt_cause.pop(item, None)
        start = self.clock.now()
        # monotonic wall-time stamps ride the root span into the flight
        # recorder: under a FakeClock every attempt collapses to the same
        # instant, so per-key serialization (attempt windows never
        # overlapping for one key) is only checkable against real time
        mono_start = time.monotonic()
        outcome = "error"
        root_span: Optional[tracing.Span] = None
        try:
            with _TRACER.start_span(
                "reconcile",
                attributes={
                    "controller": reg_name,
                    "namespace": req.namespace,
                    "name": req.name,
                    "attempt": attempt,
                },
                trace_id=self._trace_ids.get(item, ""),
            ) as span:
                root_span = span
                if cause_ts is not None:
                    span.set_attribute("cause_ts", cause_ts)
                if span.recording and item not in self._trace_ids:
                    self._trace_ids[item] = span.trace_id
                try:
                    result = reg.reconciler.reconcile(req) or Result()
                    if result.requeue_after > 0:
                        outcome = "requeue_after"
                    elif result.requeue:
                        outcome = "requeue"
                    else:
                        outcome = "success"
                    span.set_attribute("reconcile.result", outcome)
                    with self._lock:
                        self._retries.pop(item, None)
                    if not alive():
                        self._clear_request_trace(item)
                        return
                    if result.requeue_after > 0:
                        # explicit schedule: Forget (controller-runtime does
                        # on RequeueAfter) and wait out the caller's delay
                        self._limiter.forget(item)
                        self._clear_request_trace(item)
                        with self._lock:
                            self._delayed.append(
                                _Delayed(self.clock.now() + result.requeue_after,
                                         reg_name, req)
                            )
                    elif result.requeue:
                        # AddRateLimited without Forget: repeated
                        # requeue=True backs off like a failure would
                        self._requeue_rate_limited(item)
                    else:
                        self._limiter.forget(item)
                        self._clear_request_trace(item)
                except Exception as err:  # controller-runtime: requeue w/ backoff
                    outcome = "error"
                    span.set_attribute("error", True)
                    span.set_attribute("reconcile.result", "error")
                    span.add_event("reconcile.error", {
                        "exception.type": type(err).__name__,
                        "exception.message": str(err),
                    })
                    if not alive():
                        self._clear_request_trace(item)
                        return
                    with self._lock:
                        count = self._retries.get(item, 0) + 1
                        self._retries[item] = count
                    if count <= reg.max_retries:
                        delay = self._requeue_rate_limited(item)
                        logger.warning(
                            "reconcile %s %s failed (attempt %d, retry in "
                            "%.3fs): %s",
                            reg_name, req, count, delay, err,
                        )
                    else:
                        logger.error(
                            "reconcile %s %s dropped after %d attempts:\n%s",
                            reg_name, req, count, traceback.format_exc(),
                        )
                        with self._lock:
                            self._errors.append((reg_name, req, err))
                            # fresh budget for future events
                            self._retries.pop(item, None)
                        self._limiter.forget(item)
                        self._clear_request_trace(item)
        finally:
            duration = max(self.clock.now() - start, 0.0)
            # exemplar the duration histograms with this attempt's trace so
            # an OpenMetrics scrape can pivot from a latency bucket to the
            # recorded trace (/debug/traces/<trace_id>)
            ex = ({"trace_id": root_span.trace_id}
                  if root_span is not None and root_span.trace_id else None)
            self.reconcile_time.labels(reg_name).observe(duration,
                                                         exemplar=ex)
            self.work_duration.labels(reg_name).observe(duration,
                                                        exemplar=ex)
            self.reconcile_total.labels(reg_name, outcome).inc()
            if root_span is not None:
                # real-time execution window for the flight recorder's
                # per-key overlap check (set after export on purpose:
                # diagnostic bookkeeping, not trace payload)
                root_span.set_attribute("mono_start", mono_start)
                root_span.set_attribute("mono_end", time.monotonic())
                try:
                    rec = self.flight_recorder.record(root_span)
                    if rec is not None and self.slo_engine is not None:
                        # attempt stream -> SLO engine: errored/slow
                        # attempts become the exemplar trace an alert
                        # links back into this very recorder
                        self.slo_engine.observe_attempt(rec)
                    if rec is not None and self.lifecycle is not None:
                        # attempt stream -> lifecycle ledger: the stage
                        # partition behind /debug/criticalpath
                        self.lifecycle.observe_attempt(
                            rec, root_span, self.manager_id)
                    if rec is not None and self.metering is not None:
                        # attempt stream -> metering ledger: latches the
                        # per-tenant exemplar trace a fired fairness
                        # alert resolves at /debug/traces
                        self.metering.observe_attempt(rec)
                    if rec is not None and self.diagnosis is not None:
                        # attempt stream -> diagnosis engine: injected
                        # faults / promotions / recoveries become the
                        # discrete timeline change points correlate to
                        self.diagnosis.observe_attempt(rec)
                except Exception:  # noqa: BLE001 — observability must
                    # never take the reconcile loop down with it
                    logger.exception("flight recorder rejected a span")

    def _clear_request_trace(self, item: tuple[str, Request]) -> None:
        """The retry chain for this request is over (success, scheduled
        requeue_after, drop, or unregister): the next event starts a fresh
        trace with attempt 1."""
        with self._lock:
            self._trace_ids.pop(item, None)
            self._attempt_seq.pop(item, None)

    def _requeue_rate_limited(self, item: tuple[str, Request]) -> float:
        """Re-enqueue through the workqueue rate limiter: per-item
        exponential backoff with jitter, bounded overall by the token
        bucket.  Zero delay (bucket not empty, first failure with a tiny
        base) still round-trips the delayed queue so a hot-looping
        reconciler cannot starve the rest of the queue."""
        reg_name, req = item
        delay = max(self._limiter.when(item), 0.0)
        with self._lock:
            self._retry_totals[reg_name] = \
                self._retry_totals.get(reg_name, 0) + 1
            self._last_backoff[reg_name] = delay
            self._delayed.append(
                _Delayed(self.clock.now() + delay, reg_name, req, retry=True,
                         enqueued_at=self.clock.now()))
        return delay

    def _drain_step(self) -> int:
        """One drain step: process up to `workers` distinct-key requests —
        concurrently when workers > 1 — and return how many ran.  The
        per-batch barrier keeps clock advancement (run_until_idle/settle)
        single-threaded: no worker is mid-reconcile while the FakeClock
        jumps over a backoff window."""
        self._ensure_sources()
        self._promote_delayed()
        batch: list[tuple[str, Request]] = []
        while len(batch) < self.workers:
            item = self._pop()
            if item is None:
                break
            batch.append(item)
        if not batch:
            return 0
        if len(batch) == 1:
            item = batch[0]
            try:
                self._process_item(item)
            finally:
                self._done(item)
            return 1
        threads = [
            threading.Thread(target=self._run_item, args=(it,),
                             name=f"kube-worker-{i}", daemon=True)
            for i, it in enumerate(batch)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return len(batch)

    def _run_item(self, item: tuple[str, Request]) -> None:
        try:
            self._process_item(item)
        except Exception:  # noqa: BLE001 — same contract as the start()
            # loop: a bookkeeping bug must not strand the batch barrier
            logger.exception("worker failed outside the reconcile handler; "
                             "continuing")
        finally:
            self._done(item)

    def run_until_idle(self, max_iterations: int = 10_000,
                       advance_clock: bool = True) -> int:
        """Drain the workqueue; returns number of reconciles executed.

        Retry backoffs (error retries, rate-limited requeues) are part of
        draining: with `advance_clock=True` (default) and an injected
        FakeClock, the clock advances to each retry's due time so backoff
        stays real AND deterministic.  requeue_after schedules are NOT
        auto-advanced — use `advance(seconds)` to move the clock and
        re-drain those, or pass advance_clock=False to observe pending
        backoff state.  With `workers > 1` each step runs a batch of
        distinct-key requests concurrently (see _drain_step)."""
        n = 0
        adv = getattr(self.clock, "advance", None)
        while True:
            ran = self._drain_step()
            if ran:
                n += ran
                if n >= max_iterations:
                    raise RuntimeError(
                        "run_until_idle: reconcile loop did not settle")
                continue
            if not advance_clock or adv is None:
                break
            with self._lock:
                retry_due = [d.due for d in self._delayed if d.retry]
            if not retry_due:
                break
            delta = min(retry_due) - self.clock.now()
            if delta > 0:
                adv(delta)
            # loop: the next drain step promotes the now-due retries
        return n

    def advance(self, seconds: float) -> int:
        """Advance a FakeClock and drain newly-due delayed requeues."""
        adv = getattr(self.clock, "advance", None)
        if adv is None:
            raise TypeError("advance() requires a FakeClock")
        adv(seconds)
        return self.run_until_idle()

    def settle(self, max_seconds: float = 3600.0,
               max_iterations: int = 100_000) -> int:
        """Drain EVERYTHING a FakeClock can reach within `max_seconds` of
        fake time: retries (run_until_idle) plus requeue_after schedules
        falling due inside the budget.  The chaos soak uses this to reach
        steady state after a fault plan drains; tests asserting on timing
        should keep using run_until_idle/advance."""
        adv = getattr(self.clock, "advance", None)
        if adv is None:
            raise TypeError("settle() requires a FakeClock")
        deadline = self.clock.now() + max_seconds
        total = 0
        while True:
            total += self.run_until_idle(max_iterations=max_iterations)
            with self._lock:
                due = [d.due for d in self._delayed]
            if not due:
                break
            nxt = min(due)
            if nxt > deadline:
                break
            delta = nxt - self.clock.now()
            if delta > 0:
                adv(delta)
        return total

    def pending_delayed(self) -> list[tuple[str, Request, float]]:
        with self._lock:
            return [(d.reg_name, d.request, d.due) for d in self._delayed]

    def inflight_requests(self) -> list[tuple[str, Request]]:
        """The (controller, request) keys currently being reconciled —
        the shard drain gate (kube/shard.py) acks a handoff only once
        none of these belongs to a departed key."""
        with self._lock:
            return list(self._processing)

    def queue_stats(self) -> dict:
        """Workqueue observability snapshot (scraped into Prometheus gauges
        by core.metrics.NotebookMetrics): per-controller queue depth,
        pending backoff count, scheduled-retry totals, last backoff delay,
        and dropped-error counts."""
        with self._lock:
            depth: dict[str, int] = {
                name: len(q) for name, q in self._queues.items() if q}
            backoff_pending: dict[str, int] = {}
            for d in self._delayed:
                if d.retry:
                    backoff_pending[d.reg_name] = \
                        backoff_pending.get(d.reg_name, 0) + 1
            errors: dict[str, int] = {}
            for reg_name, _, _ in self._errors:
                errors[reg_name] = errors.get(reg_name, 0) + 1
            now = self.clock.now()
            longest: dict[str, float] = {}
            for (reg_name, _), started in self._inflight_started.items():
                age = max(now - started, 0.0)
                if age > longest.get(reg_name, -1.0):
                    longest[reg_name] = age
            return {
                "depth": depth,
                "backoff_pending": backoff_pending,
                "retries_total": dict(self._retry_totals),
                "last_backoff_s": dict(self._last_backoff),
                "errors_total": errors,
                "longest_running_s": longest,
                "controllers": [r.name for r in self._registrations],
            }

    def workqueue_debug(self) -> dict:
        """Per-item workqueue introspection for /debug/workqueue: the live
        queue (with enqueue timestamps), every delayed item with its due
        deadline and whether it is a retry backoff or a requeue_after
        schedule, and per-item retry counts — the view queue_stats()
        aggregates away."""
        def obj(req: Request) -> str:
            return f"{req.namespace}/{req.name}"

        with self._lock:
            now = self.clock.now()
            return {
                "now": now,
                "controllers": [r.name for r in self._registrations],
                "queued": [
                    {"controller": k[0], "object": obj(k[1]),
                     "queued_for_s": max(
                         now - self._enqueued_at.get(k, now), 0.0)}
                    for q in self._queues.values() for k in q
                ],
                "processing": [
                    {"controller": k[0], "object": obj(k[1]),
                     "running_for_s": max(now - started, 0.0)}
                    for k, started in sorted(self._inflight_started.items())
                ],
                "delayed": [
                    {"controller": d.reg_name, "object": obj(d.request),
                     "due_at": d.due, "due_in_s": max(d.due - now, 0.0),
                     "retry": d.retry}
                    for d in sorted(self._delayed)
                ],
                "retries": [
                    {"controller": k[0], "object": obj(k[1]), "count": v}
                    for k, v in sorted(self._retries.items(),
                                       key=lambda kv: -kv[1])
                ],
                "depth": sum(len(q) for q in self._queues.values()),
                "backoff_pending": sum(1 for d in self._delayed if d.retry),
            }

    @property
    def dropped_errors(self) -> list[tuple[str, Request, BaseException]]:
        with self._lock:
            return list(self._errors)

    def event_latency_samples(self) -> list[float]:
        """Wall-clock event->reconcile-start latencies (seconds) of up to
        the last 2^18 event-caused reconciles, oldest first — the loadtest
        computes exact p50/p99 from these."""
        with self._lock:
            return list(self._event_latency)

    # -- readiness ------------------------------------------------------------
    @property
    def started(self) -> bool:
        """True once start() launched the worker loop (readiness gate —
        liveness is `not stopped`, see main.py /healthz vs /readyz)."""
        return self._started

    def caches_synced(self) -> bool:
        """Whether the event sources backing the workqueue are live: the
        in-memory watch session is connected (it reconnects lazily after an
        injected drop), or — on a real-cluster backend — every informer
        finished its initial list (client-go WaitForCacheSync analog)."""
        if self._watch_session is not None:
            return self._watch_session.connected
        synced = getattr(self.api, "informers_synced", None)
        if callable(synced):
            return bool(synced())
        return True

    # -- standalone threaded mode ---------------------------------------------
    def start(self, poll_interval_s: float = 0.05) -> None:
        """Spawn `workers` worker threads, each popping from the shared
        workqueue.  Per-key serialization holds across workers (an
        in-flight key parks instead of double-dispatching), so raising
        WORKQUEUE_WORKERS scales throughput without relaxing the
        one-reconcile-per-object invariant."""
        def loop() -> None:
            while not self._stop.is_set():
                try:
                    busy = self._process_one()
                except Exception:  # noqa: BLE001 — the loop must survive
                    # anything escaping the per-reconcile handler (queue
                    # bookkeeping, clock, mapping bugs): a silently-dead
                    # manager thread turns into every controller stalling,
                    # indistinguishable from a hung cluster
                    logger.exception("manager loop error; continuing")
                    busy = False
                if not busy:
                    self._stop.wait(poll_interval_s)

        self._threads = [
            threading.Thread(target=loop, daemon=True,
                             name=f"kube-manager-{i}")
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()
        self._started = True

    def stop(self) -> None:
        self._stop.set()
        # a reconciler may request shutdown from the worker thread itself
        # (e.g. the TLS-profile watcher); joining the current thread would
        # raise, and the loop exits on the event anyway
        me = threading.current_thread()
        for t in self._threads:
            if t is not me:
                t.join(timeout=5)
        self._threads = []

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def wait_until_stopped(self, timeout: Optional[float] = None) -> bool:
        """Block until stop() is called (standalone main loop); True when
        the stop event fired."""
        return self._stop.wait(timeout)
