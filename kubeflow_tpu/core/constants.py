"""Shared constants for the notebook controllers.

The reference scatters these between the controller file
(notebook_controller.go:49-64) and the legacy culler package (which remains
the source of STOP_ANNOTATION, pkg/culler/culler.go; imported by ODH at
odh notebook_controller.go:35,146).  Centralized here.
"""

DEFAULT_CONTAINER_PORT = 8888
DEFAULT_SERVING_PORT = 80
DEFAULT_FSGROUP = 100
MAX_STATEFULSET_NAME_LENGTH = 52  # name + controller hash must fit 63 chars

# annotations (user-facing API surface)
STOP_ANNOTATION = "kubeflow-resource-stopped"
ANNOTATION_REWRITE_URI = "notebooks.kubeflow.org/http-rewrite-uri"
ANNOTATION_HEADERS_REQUEST_SET = "notebooks.kubeflow.org/http-headers-request-set"
ANNOTATION_NOTEBOOK_RESTART = "notebooks.opendatahub.io/notebook-restart"
LAST_ACTIVITY_ANNOTATION = "notebooks.kubeflow.org/last-activity"
LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION = (
    "notebooks.kubeflow.org/last_activity_check_timestamp"
)
# TPU extension: set while a pre-cull checkpoint has been requested; the
# in-notebook runtime acknowledges with checkpoint-complete
ANNOTATION_CHECKPOINT_REQUESTED = "notebooks.kubeflow.org/checkpoint-requested"
ANNOTATION_CHECKPOINT_COMPLETE = "notebooks.kubeflow.org/checkpoint-complete"
# voluntary migration request (drain/defrag): the RecoveryEngine runs the
# same snapshot -> slice restart -> restore verb it uses for disruption,
# no failure required, and clears the annotation once handled.  Value is
# the trigger ("drain" or "defrag"; anything else reads as "drain").
ANNOTATION_MIGRATE = "notebooks.kubeflow.org/migrate"
# stamped onto a worker pod by the kubelet-side runtime after it restored
# the session checkpoint named by the pod's CHECKPOINT_RESTORE_* env —
# the audit trail restored-state-equivalence drills assert against
ANNOTATION_RESTORED_GENERATION = "notebooks.kubeflow.org/restored-generation"
ANNOTATION_RESTORED_DIGEST = "notebooks.kubeflow.org/restored-digest"
# the SliceScheduler's all-or-nothing placement intent (core/scheduler.py):
# JSON {"v": 1, "slices": {"<id>": {"pool": ..., "nodes": [...]}}} written
# BEFORE any slice StatefulSet exists; the workload renderer turns each
# slice's pool assignment into a nodeSelector.  Contains "notebook" so
# _propagated_annotations never copies it onto pods.
ANNOTATION_PLACEMENT = "notebooks.kubeflow.org/placement"

# replicated-kernel tier (spec.replication, core/selfheal.py promote
# verb): follower catch-up freshness is stamped onto follower pods by the
# kubelet-side runtime as it applies the checkpoint-delta stream — the
# promote verb elects the freshest caught-up follower off these stamps
ANNOTATION_REPLICA_GENERATION = "notebooks.kubeflow.org/replica-generation"
ANNOTATION_REPLICA_SEQ = "notebooks.kubeflow.org/replica-seq"
ANNOTATION_REPLICA_DIGEST = "notebooks.kubeflow.org/replica-digest"

# checkpoint-sidecar contract: env rendered into every TPU worker when
# CHECKPOINT_STORE_URI is configured (consumed by runtime/checkpoint.py)
ENV_CHECKPOINT_STORE_URI = "CHECKPOINT_STORE_URI"
ENV_CHECKPOINT_INTERVAL_S = "CHECKPOINT_INTERVAL_S"
# restore stamping: written into the recreated pods of a migrated slice
ENV_CHECKPOINT_RESTORE_URI = "CHECKPOINT_RESTORE_URI"
ENV_CHECKPOINT_RESTORE_GENERATION = "CHECKPOINT_RESTORE_GENERATION"

# replication contract: role/epoch env rendered into every worker of a
# replicated notebook.  The epoch is the fencing token — the runtime MUST
# present it on every session-store write, and the store rejects writes
# below the fence so a zombie primary can never ack state after demotion.
ENV_REPLICATION_ROLE = "REPLICATION_ROLE"
ENV_REPLICATION_EPOCH = "REPLICATION_EPOCH"
ENV_REPLICA_INDEX = "REPLICA_INDEX"
ROLE_PRIMARY = "primary"
ROLE_FOLLOWER = "follower"

# labels
WORKBENCH_LABEL = "opendatahub.io/workbenches"
NOTEBOOK_NAME_LABEL = "notebook-name"
STATEFULSET_LABEL = "statefulset"
TPU_SLICE_LABEL = "notebooks.kubeflow.org/tpu-slice"
# replica index of a replicated notebook's gang ("0" = replica 0; which
# replica is PRIMARY is a status.replication pointer, not a label — the
# pointer moves on promotion, names and labels stay stable)
REPLICA_LABEL = "notebooks.kubeflow.org/replica"

# env var injected into the notebook container
PREFIX_ENV_VAR = "NB_PREFIX"

# GKE TPU node labels
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
TPU_RESOURCE = "google.com/tpu"

# tenancy (core/scheduler.py admission gate, core/preemption.py): stamped
# while a gang is held back by quota / fair share.  Value is JSON
# {"since": <clock>, "priority": <class>, "reason": "quota"|"fair-share"|
# "ordered"|"preempted"} — `since` feeds the aged fair-share dequeue score
# so queue order is deterministic and starvation-free.  Contains
# "notebooks.kubeflow.org" so _propagated_annotations never copies it onto
# pods.
ANNOTATION_QUEUED = "notebooks.kubeflow.org/queued"

# cluster-scoped tenancy policy + write-ahead preemption bookkeeping
# object: spec holds per-namespace chip quota / fair-share weight /
# default priority, status.preemptions holds in-flight preemption records
# (written BEFORE any teardown, same optimistic-concurrency RMW pattern
# as TPUWarmPool) so a manager crash or shard failover resumes — never
# repeats — an eviction.  Singleton named TENANTQUOTA_NAME.
TENANTQUOTA_KIND = "TenantQuota"
TENANTQUOTA_NAME = "default"
PREEMPTION_PENDING = "Pending"
PREEMPTION_DONE = "Done"

# warm-pool bookkeeping object (core/scheduler.py): one cluster-scoped
# TPUWarmPool per accelerator/topology shape; claim/release state lives in
# its status so it survives manager crash and leader failover
WARMPOOL_KIND = "TPUWarmPool"
WARMSLICE_PROVISIONING = "Provisioning"
WARMSLICE_READY = "Ready"
WARMSLICE_CLAIMED = "Claimed"
WARMSLICE_STATES = (WARMSLICE_PROVISIONING, WARMSLICE_READY,
                    WARMSLICE_CLAIMED)
