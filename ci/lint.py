"""Stdlib-only static analysis: the locally-runnable core of the lint gate.

CI runs ruff + mypy (ci: lint.yaml / typecheck.yaml, the analog of the
reference's .golangci.yaml + semgrep.yaml); this script enforces the subset
that needs no third-party tooling so the gate also runs in hermetic images:

  - syntax (compile) for every source file
  - unused imports (module scope)
  - mutable default arguments
  - bare `except:` clauses
  - `except Exception: pass` silent swallows (comment-free)
  - tabs in indentation / trailing whitespace
  - f-strings with no placeholders
  - intra-repo call signatures: calls to kubeflow_tpu module-level
    functions are checked against the definition's arity and keyword
    names (conservative: undecorated plain functions without *args /
    **kwargs only) — the locally-runnable slice of what mypy's
    call-checking provides
  - plain class-method call signatures, same conservative rules: when a
    local variable is bound from a direct constructor call and never
    rebound, its method calls (and the constructor call itself, against
    __init__) are arity/keyword-checked against the exact class
  - Prometheus metric naming conventions at registration sites
    (`.counter("...")` / `.gauge("...")` / `.histogram("...")` calls):
    a `*_total` name must register a counter, and a `*_seconds` name a
    histogram or gauge — a counter-suffixed gauge breaks PromQL
    rate()/increase() silently (the bug this check was born from)
  - bare `threading.Lock()`/`RLock()` construction in kubeflow_tpu/:
    control-plane locks must be wrapped in `invariants.tracked(...)` so
    the runtime LockTracker orders them and the interleave explorer
    (kubeflow_tpu/testing/interleave.py) can schedule around them; an
    untracked lock is invisible to both.  Leaf/out-of-scope modules are
    exempted in `_BARE_LOCK_EXEMPT` with their reason
"""

from __future__ import annotations

import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
TARGETS = ["kubeflow_tpu", "tests", "ci", "conformance", "examples",
           "loadtest", "bench.py", "__graft_entry__.py"]


def iter_files():
    for t in TARGETS:
        p = ROOT / t
        if p.is_file():
            yield p
        elif p.is_dir():
            # some targets are absent in reduced contexts (the Dockerfile
            # build runs this with only kubeflow_tpu + ci copied in)
            yield from sorted(p.rglob("*.py"))


class Visitor(ast.NodeVisitor):
    def __init__(self, src: str):
        self.problems: list[tuple[int, str]] = []
        self.src = src
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()

    def visit_Import(self, node):  # noqa: N802
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node):  # noqa: N802
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imported.setdefault(a.asname or a.name, node.lineno)

    def visit_Name(self, node):  # noqa: N802
        self.used.add(node.id)

    def visit_Attribute(self, node):  # noqa: N802
        self.generic_visit(node)

    def visit_FunctionDef(self, node, is_async=False):  # noqa: N802
        for default in node.args.defaults + node.args.kw_defaults:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.problems.append(
                    (default.lineno, "mutable default argument"))
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self.visit_FunctionDef(node, is_async=True)

    def visit_ExceptHandler(self, node):  # noqa: N802
        if node.type is None:
            self.problems.append((node.lineno, "bare except:"))
        self.generic_visit(node)

    def visit_JoinedStr(self, node):  # noqa: N802
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.problems.append((node.lineno, "f-string without placeholders"))
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self.visit(v)

    def visit_FormattedValue(self, node):  # noqa: N802
        # visit the interpolated expression (names count as used) but not
        # the format_spec, which is itself a JoinedStr of constants and
        # must not be flagged as a placeholder-less f-string
        self.visit(node.value)
        if node.format_spec is not None:
            for part in node.format_spec.values:
                if isinstance(part, ast.FormattedValue):
                    self.visit(part)


def check(path: Path, tree: "ast.AST | None" = None) -> list[str]:
    src = path.read_text()
    rel = path.relative_to(ROOT)
    if tree is None:
        try:
            tree = ast.parse(src, filename=str(rel))
        except SyntaxError as err:
            return [f"{rel}:{err.lineno}: syntax error: {err.msg}"]
    v = Visitor(src)
    v.visit(tree)
    out = [f"{rel}:{line}: {msg}" for line, msg in v.problems]
    # unused module-scope imports: used nowhere as a name and not re-exported
    dunder_all = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    dunder_all = {getattr(e, "value", None)
                                  for e in node.value.elts}
    text_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                text_names.add(base.id)
    is_init = path.name == "__init__.py"
    for name, line in v.imported.items():
        if name.startswith("_"):
            continue
        if is_init or name in dunder_all:
            continue  # packaging re-exports
        if name not in v.used and name not in text_names and \
                f"{name}" not in src.split("import", 1)[0]:
            # annotation-only usage (string annotations) — grep fallback
            occurrences = src.count(name)
            if occurrences <= 1:
                out.append(f"{rel}:{line}: unused import {name!r}")
    for lineno, line in enumerate(src.splitlines(), 1):
        if line != line.rstrip():
            out.append(f"{rel}:{lineno}: trailing whitespace")
        if line.startswith("\t"):
            out.append(f"{rel}:{lineno}: tab indentation")
    out.extend(f"{rel}:{line}: {msg}"
               for line, msg in check_metric_names(tree))
    out.extend(f"{rel}:{line}: {msg}"
               for line, msg in check_bare_locks(tree, rel.as_posix()))
    return out


#: modules allowed to construct untracked locks, each with WHY the
#: tracker/explorer may stay blind to them (same contract as
#: ci/analyzers/allowlist.py: no entry without a reason)
_BARE_LOCK_EXEMPT = {
    "kubeflow_tpu/utils/invariants.py":
        "the TrackedLock factory and the LockTracker's own graph lock "
        "live here — wrapping them would recurse",
    "kubeflow_tpu/tpu/device_plugin.py":
        "real-node kubelet daemon; its message buffer lock never meets "
        "a control-plane lock or a model-checked schedule",
    "kubeflow_tpu/kube/client.py":
        "real-apiserver HTTP client: locks guard private watch/session "
        "plumbing on the wire side and never nest with store locks",
    "kubeflow_tpu/kube/wire.py":
        "wire-protocol server internals (per-connection snapshots, "
        "audit log); self-contained leaf locks on the serving path",
    "kubeflow_tpu/core/sessionstate.py":
        "leaf RLock around the in-memory snapshot ring, never held "
        "across a call into another subsystem; the model-checked "
        "restore protocol serializes on store.commit yield points, not "
        "on this lock",
    "kubeflow_tpu/utils/tracing.py":
        "telemetry leaf locks (span buffers, provider registry); "
        "tracking them would inject a yield point into every span "
        "start and blow up the explored schedule space with "
        "control-flow-irrelevant interleavings",
    "kubeflow_tpu/utils/metrics.py":
        "telemetry leaf locks around metric registries/series — same "
        "rationale as tracing.py",
    "kubeflow_tpu/utils/profiler.py":
        "sampler leaf lock on the real-wall-time profiling path — "
        "same rationale as tracing.py",
    "kubeflow_tpu/utils/flightrecorder.py":
        "flight-recorder ring lock, append-only diagnostics — same "
        "rationale as tracing.py",
    "kubeflow_tpu/utils/slo.py":
        "SLO engine sample-window lock, telemetry only — same "
        "rationale as tracing.py",
    "kubeflow_tpu/utils/lifecycle.py":
        "lifecycle-ledger leaf lock (attempt fold + read-side "
        "snapshots), telemetry only — same rationale as tracing.py",
    "kubeflow_tpu/utils/tsdb.py":
        "time-series ring lock, append/query telemetry only — same "
        "rationale as tracing.py",
    "kubeflow_tpu/utils/metering.py":
        "tenant-metering ledger leaf lock (census fold + read-side "
        "snapshots), telemetry only — same rationale as tracing.py",
}

_LOCK_CTORS = ("threading.Lock", "threading.RLock")


def check_bare_locks(tree: ast.AST, rel: str) -> list[tuple[int, str]]:
    """Flag `threading.Lock()`/`RLock()` constructions in kubeflow_tpu/
    that are not passed straight into `invariants.tracked(...)`."""
    if not rel.startswith("kubeflow_tpu/") or rel in _BARE_LOCK_EXEMPT:
        return []
    wrapped: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _dotted_name(node.func).split(".")[-1] == "tracked":
            for arg in node.args:
                if isinstance(arg, ast.Call):
                    wrapped.add(id(arg))
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _dotted_name(node.func) in _LOCK_CTORS and \
                id(node) not in wrapped:
            out.append((
                node.lineno,
                "bare %s() — wrap it in invariants.tracked(...) so the "
                "LockTracker and the interleave explorer see it, or add "
                "this module to _BARE_LOCK_EXEMPT with a reason"
                % _dotted_name(node.func)))
    return out


def _dotted_name(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    parts.append(node.id)
    return ".".join(reversed(parts))


_METRIC_METHODS = ("counter", "gauge", "histogram")


def check_metric_names(tree: ast.AST) -> list[tuple[int, str]]:
    """Prometheus naming conventions at registration sites: `*_total` names
    must be counters; `*_seconds` names must be histograms or gauges
    (duration counters like `*_seconds_total` are fine — the `_total` rule
    covers them)."""
    problems: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        method = node.func.attr
        if name.endswith("_total") and method != "counter":
            problems.append((
                node.lineno,
                f"metric {name!r} has the counter suffix _total but is "
                f"registered via .{method}() — register a counter or "
                "rename"))
        elif name.endswith("_seconds") and method == "counter":
            problems.append((
                node.lineno,
                f"metric {name!r} is a duration (_seconds) but is "
                "registered via .counter() — use a histogram or gauge "
                "(or name it *_seconds_total)"))
    return problems


def _fn_spec(node: "ast.FunctionDef", drop_self: bool = False):
    """(min_pos, max_pos, kwonly_required, all_kw_names, pos_names) for a
    CHECKABLE function: no decorators, no *args / **kwargs (the caller
    filters); drop_self strips the bound first arg for methods."""
    a = node.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    if drop_self and pos:
        pos = pos[1:]
    n_default = len(a.defaults)
    kwonly = [p.arg for p in a.kwonlyargs]
    kwonly_required = {
        p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults)
        if d is None}
    return (len(pos) - n_default, len(pos),
            kwonly_required, set(pos) | set(kwonly), pos)


def _collect_signatures() -> tuple[dict, dict]:
    """(module_sigs, method_sigs):
    module_sigs: module path ('kubeflow_tpu.models.generate') ->
    {fn_name: spec} for CHECKABLE module-level functions: no decorators,
    no *args / **kwargs, not nested.
    method_sigs: module path -> {ClassName: {method_name: spec}} for
    plain instance methods under the same conservative rules (self
    dropped from the spec; staticmethod/classmethod/property carry
    decorators, so they are excluded by the no-decorator rule)."""
    sigs: dict[str, dict] = {}
    method_sigs: dict[str, dict] = {}
    pkg = ROOT / "kubeflow_tpu"
    for path in sorted(pkg.rglob("*.py")):
        rel = path.relative_to(ROOT).with_suffix("")
        module = ".".join(rel.parts)
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        table = {}
        classes: dict[str, dict] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                methods = {}
                for item in node.body:
                    if not isinstance(item, ast.FunctionDef) \
                            or item.decorator_list:
                        continue
                    a = item.args
                    if a.vararg or a.kwarg:
                        continue
                    if not (a.posonlyargs + a.args) or \
                            (a.posonlyargs + a.args)[0].arg != "self":
                        continue
                    methods[item.name] = _fn_spec(item, drop_self=True)
                if methods:
                    classes[node.name] = methods
                continue
            if not isinstance(node, ast.FunctionDef) or node.decorator_list:
                continue
            a = node.args
            if a.vararg or a.kwarg:
                continue
            table[node.name] = _fn_spec(node)
        if table:
            sigs[module] = table
        if classes:
            method_sigs[module] = classes
    return sigs, method_sigs


def _check_callsite(name: str, spec, node: "ast.Call"):
    """Shared arity/keyword validation for one call site against a spec
    from _fn_spec.  Returns [(line, msg), ...]."""
    problems = []
    min_pos, max_pos, kwonly_required, all_kw, pos_names = spec
    if any(isinstance(a, ast.Starred) for a in node.args) or \
            any(k.arg is None for k in node.keywords):
        return problems  # *args / **kwargs at the call site: not checkable
    n_pos = len(node.args)
    kw_names = {k.arg for k in node.keywords}
    if n_pos > max_pos:
        problems.append(
            (node.lineno,
             f"call to {name}(): {n_pos} positional args, "
             f"definition takes at most {max_pos}"))
    if n_pos + len(kw_names & set(pos_names)) < min_pos:
        problems.append(
            (node.lineno,
             f"call to {name}(): too few arguments "
             f"(needs {min_pos} required positional)"))
    unknown = kw_names - all_kw
    if unknown:
        problems.append(
            (node.lineno,
             f"call to {name}(): unknown keyword(s) "
             f"{sorted(unknown)}"))
    missing = kwonly_required - kw_names
    if missing:
        problems.append(
            (node.lineno,
             f"call to {name}(): missing required keyword-only "
             f"arg(s) {sorted(missing)}"))
    return problems


class CallChecker(ast.NodeVisitor):
    """Check direct calls to imported kubeflow_tpu module functions."""

    def __init__(self, sigs: dict, tree: ast.AST):
        self.problems: list[tuple[int, str]] = []
        self.targets: dict[str, tuple] = {}   # local name -> spec
        # module-level imports only: function-local imports and any name
        # rebound at module scope (def/class/assign) must not be checked
        # against the package signature
        for node in tree.body:
            if not isinstance(node, ast.ImportFrom) or not node.module:
                continue
            table = sigs.get(node.module)
            # relative imports inside the package: resolve best-effort by
            # suffix match (unique or nothing)
            if table is None and node.level:
                cands = [m for m in sigs
                         if m.endswith("." + node.module)]
                table = sigs[cands[0]] if len(cands) == 1 else None
            if not table:
                continue
            for alias in node.names:
                if alias.name in table:
                    self.targets[alias.asname or alias.name] = (
                        alias.name, table[alias.name])
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self.targets.pop(node.name, None)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.targets.pop(t.id, None)

    def visit_Call(self, node):  # noqa: N802
        self.generic_visit(node)
        if not isinstance(node.func, ast.Name):
            return
        spec = self.targets.get(node.func.id)
        if spec is None:
            return
        name, sig = spec
        self.problems.extend(_check_callsite(name, sig, node))


class MethodCallChecker:
    """Arity checking for PLAIN CLASS METHODS, the class-method analog of
    CallChecker.  The exact class of a receiver is only known statically
    when the variable was bound from a direct constructor call in the
    SAME scope (`mgr = Manager(...)` ... `mgr.start(...)`) and never
    rebound in between — so that's precisely what gets checked, plus the
    constructor call itself against `__init__`.  Same conservative rules
    as the function checker: undecorated classes, undecorated methods
    with a literal `self` first arg, no *args/**kwargs on either side.
    Method lookup is exact-class only (no MRO walk): a method the class
    inherits is skipped, and subclass overrides can't mislead because
    the constructor names the exact class."""

    def __init__(self, method_sigs: dict, tree: ast.AST, path: Path):
        self.problems: list[tuple[int, str]] = []
        # class name visible in this file -> {method: spec}
        self.classes: dict[str, dict] = {}
        for node in tree.body:
            if not isinstance(node, ast.ImportFrom) or not node.module:
                continue
            table = method_sigs.get(node.module)
            if table is None and node.level:
                cands = [m for m in method_sigs
                         if m.endswith("." + node.module)]
                table = method_sigs[cands[0]] if len(cands) == 1 else None
            if not table:
                continue
            for alias in node.names:
                if alias.name in table:
                    self.classes[alias.asname or alias.name] = \
                        table[alias.name]
        # classes defined in THIS file (any target dir, tests included)
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and not node.decorator_list:
                methods = {}
                for item in node.body:
                    if not isinstance(item, ast.FunctionDef) \
                            or item.decorator_list:
                        continue
                    a = item.args
                    if a.vararg or a.kwarg:
                        continue
                    if not (a.posonlyargs + a.args) or \
                            (a.posonlyargs + a.args)[0].arg != "self":
                        continue
                    methods[item.name] = _fn_spec(item, drop_self=True)
                if methods:
                    self.classes[node.name] = methods

    def check(self, tree: ast.AST) -> None:
        self._check_scope(tree.body)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_scope(node.body)

    def _check_scope(self, body) -> None:
        bindings: dict[str, tuple[str, dict]] = {}  # var -> (cls, methods)
        self._walk(body, bindings)

    def _walk(self, stmts, bindings) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                bindings.pop(stmt.name, None)
                continue  # nested scope: checked on its own
            self._scan_calls(stmt, bindings)
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    self._rebind(t, stmt.value, bindings)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(stmt.target, ast.Name):
                    bindings.pop(stmt.target.id, None)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._invalidate(stmt.target, bindings)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        self._invalidate(item.optional_vars, bindings)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._invalidate(t, bindings)
            # recurse into compound bodies with the same binding map
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub and isinstance(sub, list):
                    self._walk(sub, bindings)
            for h in getattr(stmt, "handlers", ()) or ():
                if h.name:
                    bindings.pop(h.name, None)
                self._walk(h.body, bindings)

    def _rebind(self, target, value, bindings) -> None:
        if not isinstance(target, ast.Name):
            self._invalidate(target, bindings)
            return
        cls = None
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
            cls = value.func.id if value.func.id in self.classes else None
        if cls is not None:
            bindings[target.id] = (cls, self.classes[cls])
        else:
            bindings.pop(target.id, None)

    def _invalidate(self, target, bindings) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                bindings.pop(node.id, None)

    def _scan_calls(self, stmt, bindings) -> None:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # constructor arity against __init__
            if isinstance(func, ast.Name) and func.id in self.classes:
                init = self.classes[func.id].get("__init__")
                if init is not None:
                    self.problems.extend(
                        _check_callsite(func.id, init, node))
            # bound-method call on a constructor-typed local
            if isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name):
                bound = bindings.get(func.value.id)
                if bound is None:
                    continue
                cls, methods = bound
                spec = methods.get(func.attr)
                if spec is None:
                    continue  # inherited or dynamic: out of scope
                self.problems.extend(_check_callsite(
                    f"{cls}.{func.attr}", spec, node))


def check_calls(path: Path, sigs: dict, method_sigs: dict,
                tree: ast.AST) -> list[str]:
    rel = path.relative_to(ROOT)
    checker = CallChecker(sigs, tree)
    checker.visit(tree)
    problems = list(checker.problems)
    mchecker = MethodCallChecker(method_sigs, tree, path)
    mchecker.check(tree)
    problems.extend(mchecker.problems)
    return [f"{rel}:{line}: {msg}" for line, msg in sorted(problems)]


def main() -> int:
    failures = []
    count = 0
    sigs, method_sigs = _collect_signatures()
    for path in iter_files():
        count += 1
        try:
            tree = ast.parse(path.read_text(),
                             filename=str(path.relative_to(ROOT)))
        except SyntaxError as err:
            failures.append(f"{path.relative_to(ROOT)}:{err.lineno}: "
                            f"syntax error: {err.msg}")
            continue
        failures.extend(check(path, tree))
        failures.extend(check_calls(path, sigs, method_sigs, tree))
    for f in failures:
        print(f)
    print(f"lint: {count} files, {len(failures)} problems")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
