"""Flash-attention numerics gate: Pallas kernel vs XLA reference on TPU.

Run on the real chip before every bench (ci/bench_smoke.sh): for the shapes
and block-size configs the bench hot path uses, assert forward outputs AND
input gradients of `ops.attention.flash_attention` match `xla_attention`
within bf16 tolerance.  Exits non-zero on mismatch so a kernel/tiling bug
can never ship inside a tuned BENCH_CHIP config.

The reference has no analog (its hot path is an HTTP probe); this is the
TPU-native equivalent of pinning the data plane before tuning it.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import flash_attention, xla_attention

# (batch, seq, q_heads, kv_heads, head_dim) — BENCH_CHIP attention shape
# (12 heads x 128) plus a GQA variant and a short-seq edge case.
SHAPES = [
    (2, 2048, 12, 12, 128),
    (2, 1024, 16, 4, 128),
    (2, 256, 4, 4, 128),
]
# block_q/block_k configs the MFU sweep explores (0 = kernel default).
BLOCKS = [(0, 0), (256, 256), (512, 512), (1024, 1024), (512, 1024)]


def _max_err(a: jax.Array, b: jax.Array) -> float:
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


def check(batch, seq, heads, kv_heads, head_dim, block_q, block_k) -> list[str]:
    key = jax.random.PRNGKey(seq + heads + block_q)
    kq, kk, kv, kg = jax.random.split(key, 4)
    shape_q = (batch, seq, heads, head_dim)
    shape_kv = (batch, seq, kv_heads, head_dim)
    q = jax.random.normal(kq, shape_q, jnp.bfloat16)
    k = jax.random.normal(kk, shape_kv, jnp.bfloat16)
    v = jax.random.normal(kv, shape_kv, jnp.bfloat16)
    cot = jax.random.normal(kg, shape_q, jnp.bfloat16)

    def fwd_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=block_q, block_k=block_k)

    def fwd_xla(q, k, v):
        return xla_attention(q, k, v, causal=True)

    out_f, vjp_f = jax.vjp(jax.jit(fwd_flash), q, k, v)
    out_x, vjp_x = jax.vjp(jax.jit(fwd_xla), q, k, v)
    grads_f = vjp_f(cot)
    grads_x = vjp_x(cot)

    # bf16 inputs, fp32 softmax accumulation in both paths: outputs agree to
    # bf16 resolution; gradients accumulate one extra matmul of rounding.
    failures = []
    err = _max_err(out_f, out_x)
    if err > 3e-2:
        failures.append(f"fwd max_err={err:.4f}")
    for name, gf, gx in zip("qkv", grads_f, grads_x):
        err = _max_err(gf, gx)
        if err > 6e-2:
            failures.append(f"d{name} max_err={err:.4f}")
    return failures


def main() -> int:
    if jax.default_backend() != "tpu":
        print("flash numerics: no TPU backend, skipping (pallas kernel is TPU-only)")
        return 0
    bad = 0
    for batch, seq, heads, kv_heads, head_dim in SHAPES:
        for block_q, block_k in BLOCKS:
            if block_q > seq or block_k > seq:
                continue
            failures = check(batch, seq, heads, kv_heads, head_dim, block_q, block_k)
            tag = (
                f"b{batch} s{seq} h{heads}/{kv_heads} d{head_dim} "
                f"blocks=({block_q},{block_k})"
            )
            if failures:
                bad += 1
                print(f"FAIL {tag}: {'; '.join(failures)}")
            else:
                print(f"ok   {tag}")
    if bad:
        print(f"flash numerics: {bad} config(s) FAILED")
        return 1
    print("flash numerics: all configs match the XLA reference")
    return 0


if __name__ == "__main__":
    sys.exit(main())
