"""Stdlib-only static analysis: the locally-runnable core of the lint gate.

CI runs ruff + mypy (ci: lint.yaml / typecheck.yaml, the analog of the
reference's .golangci.yaml + semgrep.yaml); this script enforces the subset
that needs no third-party tooling so the gate also runs in hermetic images:

  - syntax (compile) for every source file
  - unused imports (module scope)
  - mutable default arguments
  - bare `except:` clauses
  - `except Exception: pass` silent swallows (comment-free)
  - tabs in indentation / trailing whitespace
  - f-strings with no placeholders
"""

from __future__ import annotations

import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
TARGETS = ["kubeflow_tpu", "tests", "ci", "conformance", "examples",
           "loadtest", "bench.py", "__graft_entry__.py"]


def iter_files():
    for t in TARGETS:
        p = ROOT / t
        if p.is_file():
            yield p
        else:
            yield from sorted(p.rglob("*.py"))


class Visitor(ast.NodeVisitor):
    def __init__(self, src: str):
        self.problems: list[tuple[int, str]] = []
        self.src = src
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()

    def visit_Import(self, node):  # noqa: N802
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node):  # noqa: N802
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imported.setdefault(a.asname or a.name, node.lineno)

    def visit_Name(self, node):  # noqa: N802
        self.used.add(node.id)

    def visit_Attribute(self, node):  # noqa: N802
        self.generic_visit(node)

    def visit_FunctionDef(self, node, is_async=False):  # noqa: N802
        for default in node.args.defaults + node.args.kw_defaults:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.problems.append(
                    (default.lineno, "mutable default argument"))
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self.visit_FunctionDef(node, is_async=True)

    def visit_ExceptHandler(self, node):  # noqa: N802
        if node.type is None:
            self.problems.append((node.lineno, "bare except:"))
        self.generic_visit(node)

    def visit_JoinedStr(self, node):  # noqa: N802
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.problems.append((node.lineno, "f-string without placeholders"))
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self.visit(v)

    def visit_FormattedValue(self, node):  # noqa: N802
        # visit the interpolated expression (names count as used) but not
        # the format_spec, which is itself a JoinedStr of constants and
        # must not be flagged as a placeholder-less f-string
        self.visit(node.value)
        if node.format_spec is not None:
            for part in node.format_spec.values:
                if isinstance(part, ast.FormattedValue):
                    self.visit(part)


def check(path: Path) -> list[str]:
    src = path.read_text()
    rel = path.relative_to(ROOT)
    try:
        tree = ast.parse(src, filename=str(rel))
    except SyntaxError as err:
        return [f"{rel}:{err.lineno}: syntax error: {err.msg}"]
    v = Visitor(src)
    v.visit(tree)
    out = [f"{rel}:{line}: {msg}" for line, msg in v.problems]
    # unused module-scope imports: used nowhere as a name and not re-exported
    dunder_all = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    dunder_all = {getattr(e, "value", None)
                                  for e in node.value.elts}
    text_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                text_names.add(base.id)
    is_init = path.name == "__init__.py"
    for name, line in v.imported.items():
        if name.startswith("_"):
            continue
        if is_init or name in dunder_all:
            continue  # packaging re-exports
        if name not in v.used and name not in text_names and \
                f"{name}" not in src.split("import", 1)[0]:
            # annotation-only usage (string annotations) — grep fallback
            occurrences = src.count(name)
            if occurrences <= 1:
                out.append(f"{rel}:{line}: unused import {name!r}")
    for lineno, line in enumerate(src.splitlines(), 1):
        if line != line.rstrip():
            out.append(f"{rel}:{lineno}: trailing whitespace")
        if line.startswith("\t"):
            out.append(f"{rel}:{lineno}: tab indentation")
    return out


def main() -> int:
    failures = []
    count = 0
    for path in iter_files():
        count += 1
        failures.extend(check(path))
    for f in failures:
        print(f)
    print(f"lint: {count} files, {len(failures)} problems")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
